"""Online CHSAC-AF training loop: scan chunks interleaved with SAC updates.

The reference trains one SAC step per job completion inside its Python event
loop (`/root/reference/simcore/simulator_paper_multi.py:757-810`).  Here the
simulator runs as jitted scan chunks; between chunks the chunk's transition
stream is scattered into the device replay buffer and the number of train
steps equals the number of newly-finished (valid) transitions — same
updates-per-experience schedule, but with both rollout and update compiled.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import numpy as np

from ..models.structs import FleetSpec, SimParams
from ..obs.health import RunAbort
from ..sim.io import CSVWriters, drain_emissions
from ..sim.engine import Engine, init_state
from .agent import CHSAC_AF

_WM_LIKE = {"cluster": 0, "job": 0}  # CSV byte-watermark checkpoint subtree

#: subdirectory (under ckpt_dir) for the forensic checkpoint a RunAbort
#: saves — outside the ``step_*`` namespace, so ``latest_step`` / resume
#: never mistake the aborted state for the last HEALTHY checkpoint (the
#: campaign driver rolls back to the healthy one and keeps this for the
#: post-mortem)
ABORT_CKPT_SUBDIR = "aborted"


def _interrupted(shutdown) -> bool:
    return shutdown is not None and shutdown.requested


def _abort_cleanup(*, sink, state, save_fn, out_dir, algo, fleet,
                   context_fn=None, timer=None):
    """RunAbort housekeeping for the trainer loops (best-effort).

    Flushes the exporter worker and writes ``run_summary.json`` with
    ``status="aborted"`` (an abort must not strand buffered rows), then
    saves the forensic checkpoint via ``save_fn`` and the forensic
    ``abort_context.json`` via ``context_fn`` — each step independently,
    so a failed flush cannot also cost the checkpoint (and a failed
    checkpoint cannot cost the context the replay tooling reads).
    Exceptions here are logged to stderr but never mask the abort
    itself — the caller re-raises it.
    """
    import sys

    def best_effort(what, fn):
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - post-mortem, best effort
            print(f"[abort-cleanup] {what} failed: {e!r}", file=sys.stderr)
            return False
        return True

    def flush_and_stamp():
        # flush + summary BEFORE the checkpoint: the exporter rows are
        # the post-mortem; a checkpoint failure must not strand them
        # (offsets read after finalize still see the flushed files)
        from ..obs.export import host_phase_seconds

        hp = host_phase_seconds(timer)
        if sink is not None:
            sink.finalize(state, status="aborted", host_phases=hp)
        elif out_dir:
            from ..obs.export import write_status_summary

            write_status_summary(out_dir, algo=algo, fleet=fleet,
                                 state=state, status="aborted",
                                 host_phases=hp)

    if not best_effort("exporter flush / aborted summary", flush_and_stamp):
        if sink is not None:
            sink.close(abort=True)
    if save_fn is not None:
        best_effort("forensic checkpoint", save_fn)
    if context_fn is not None:
        best_effort("abort context", context_fn)


def _ckpt_metadata(fleet, params, fingerprint: str, chunk: int) -> Dict:
    """Run-identity metadata stamped into the checkpoint manifest.

    Enough for an operator (or the forensic replay) to answer "which run
    wrote this, under which chaos realization, at which chunk" from the
    store alone: seed, params fingerprint, chaos stage/reseed, workload
    name, chunk index."""
    cur = (params.faults.curriculum
           if params.faults is not None else None)
    return {
        "seed": int(params.seed),
        "algo": params.algo,
        "chunk": int(chunk),
        "params_fingerprint": fingerprint,
        "chaos": ({"name": cur.name, "stage": int(cur.stage),
                   "reseed": int(cur.reseed)} if cur is not None else None),
        "workload": (params.workload.name
                     if params.workload is not None else None),
    }


def _write_abort_ctx(bundle_dir, *, error, chunk, chunk_steps, fleet, params,
                     trees, train=None):
    from ..sim.replay import write_abort_context

    write_abort_context(bundle_dir, error=error, chunk=chunk,
                        chunk_steps=chunk_steps, fleet=fleet, params=params,
                        trees=trees, train=train)


def _wm_like(params) -> Dict[str, int]:
    """Watermark template for this run shape (fault runs add fault_log.csv;
    obs-enabled runs add the metrics.jsonl byte offset — the checkpoint
    subtree is structural, so the key set must be a pure function of
    params)."""
    wm = dict(_WM_LIKE)
    if params.faults is not None and params.faults.enabled:
        wm["fault"] = 0
    if params.obs_enabled:
        wm["obs_jsonl"] = 0
    return wm


def _save_watermark(params, writers, sink) -> Dict[str, int]:
    """The checkpoint's byte-watermark subtree: CSV offsets + (obs runs)
    the flushed metrics.jsonl offset."""
    wm = writers.offsets() if writers else _wm_like(params)
    if params.obs_enabled:
        wm["obs_jsonl"] = sink.offsets()["obs_jsonl"] if sink else 0
    return wm


def _open_writers(out_dir: Optional[str], fleet: FleetSpec, start_chunk: int,
                  csv_watermark: Optional[Dict[str, int]],
                  params=None) -> Optional[CSVWriters]:
    """CSV writers for a (possibly resumed) run: append on resume, truncating
    back to the checkpoint's byte watermark so rows a crashed run wrote past
    its last checkpoint aren't duplicated."""
    if not out_dir:
        return None
    fault_cols = (params is not None and params.faults is not None
                  and params.faults.enabled)
    signal_cols = (params is not None and params.workload is not None
                   and params.workload.signals is not None)
    writers = CSVWriters(out_dir, fleet, append=start_chunk > 0,
                         fault_cols=fault_cols, signal_cols=signal_cols)
    if csv_watermark is not None:
        writers.truncate_to(csv_watermark)
    return writers


def _open_sink(obs, fleet: FleetSpec, params, state=None, watermark=None):
    """ObsSink for a trainer loop (None without an ObsConfig).

    The sink accepts the trainer's device-side emission pytrees directly
    — its background worker pays the transfer off the critical path.  On
    an unwinding exception the worker is a daemon thread and dies with
    the process; the normal exit path calls ``sink.finalize(state)``.
    Pass the (possibly checkpoint-restored) ``state`` so the watchdog
    baseline is primed from its cumulative counters, and the restored
    byte-watermark dict so ``metrics.jsonl`` appends from the restored
    tick instead of restarting (CSV resume parity).
    """
    if obs is None:
        return None
    from ..obs.export import ObsSink

    wm = (watermark or {}).get("obs_jsonl")
    return ObsSink.open(obs, fleet=fleet, params=params, state=state,
                        jsonl_watermark=None if wm is None else int(wm))


def _run_log(out_dir: Optional[str]):
    """project.log logger for in-run RL notices (None without an out_dir)."""
    if not out_dir:
        return None
    from ..utils.logging import get_logger

    return get_logger(out_dir)


def _log_rl_chunk(log, chunk: int, t: float, metrics, n_new: int) -> None:
    """Per-train-chunk RL metric line (reference parity: the torch loop
    logs its metrics dict on every train call,
    `/root/reference/simcore/simulator_paper_multi.py:755,807`; here
    updates are fused per chunk, so one line summarizes the chunk)."""
    if log is None or metrics is None:
        return
    log.info(
        "rl-update chunk=%d t=%.0f n_new=%d critic_loss=%.6g "
        "actor_loss=%.6g alpha=%.4g entropy=%.4g lambda=%s violation=%s",
        chunk, t, n_new,
        float(np.asarray(metrics.get("critic_loss", np.nan))),
        float(np.asarray(metrics.get("actor_loss", np.nan))),
        float(np.asarray(metrics.get("alpha", np.nan))),
        float(np.asarray(metrics.get("entropy", np.nan))),
        np.asarray(metrics.get("lambda", np.nan)).tolist(),
        np.asarray(metrics.get("violation", np.nan)).tolist(),
    )


def _log_preempt_notices(log, emissions, limit: int = 50) -> None:
    """Preempt/resume notices for jobs that finished with preemptions.

    The reference logs at preemption/resume time
    (`simulator_paper_multi.py:835, 387`); the scanned engine's host only
    sees the emission stream, so the notice fires when the preempted job
    finishes (same information: job id, count, DC)."""
    if log is None:
        return
    jv = np.asarray(emissions["job_valid"])
    if not jv.any():
        return
    from ..sim.engine import JOB_COLS

    i_pc, i_dc = JOB_COLS.index("preempt_count"), JOB_COLS.index("dc")
    i_jid, i_lat = JOB_COLS.index("jid"), JOB_COLS.index("latency_s")
    rows = np.asarray(emissions["job"])[jv]
    pre = rows[rows[:, i_pc] > 0]
    for r in pre[:limit]:
        log.info("preempt-resume: job=%d finished after %d preemption(s) "
                 "dc=%d latency=%.3fs", int(r[i_jid]), int(r[i_pc]),
                 int(r[i_dc]), float(r[i_lat]))
    if len(pre) > limit:
        log.info("preempt-resume: ... %d more this chunk", len(pre) - limit)


def make_agent(fleet: FleetSpec, params: SimParams) -> CHSAC_AF:
    """The CLI-default CHSAC-AF agent for this (fleet, params)."""
    from .cmdp import constraints_from_params

    return CHSAC_AF(
        obs_dim=params.obs_dim(fleet.n_dc),
        n_dc=fleet.n_dc,
        n_g_choices=params.max_gpus_per_job,
        constraints=constraints_from_params(params),
        buffer_capacity=params.rl_buffer,
        batch=params.rl_batch,
        warmup=params.rl_warmup,
        seed=params.seed,
        critic_arch=params.critic_arch,
    )


def warm_sac_from_checkpoint(cfg, ckpt_dir: str, key, step=None):
    """Fresh :class:`SACState` for ``cfg`` with the encoder and actor params
    grafted from a saved training checkpoint.

    Policy-only warm start: the critic, target critic, temperature, CMDP
    multipliers, and every optimizer state stay freshly initialized — the
    donor run's critic architecture and constraint regime need not match
    the target config (e.g. the canonical week's `heads` critic and
    latency lambda clamped at 10 would poison an hour-scale config whose
    latency constraint IS satisfiable).  Only the obs/action dims must
    agree.  Pass the result as ``init_sac`` to
    :func:`train_chsac_distributed` / `evaluation.run_algo`.
    """
    from ..utils.checkpoint import restore_checkpoint
    from .sac import sac_init

    sac = sac_init(cfg, key)
    # raw full restore: a typed partial restore needs a template matching
    # the DONOR's critic arch, which this helper deliberately does not
    # require.  The checkpoint's replay/sim trees are materialized on host
    # once and freed immediately below — transient, but callers grafting
    # from checkpoints with very large replay shards should expect the
    # restore peak to scale with the donor's replay capacity.
    # step=None walks the verified fallback chain: a corrupt newest
    # checkpoint in the donor store degrades the graft to the previous
    # step with a logged reason (chaos_sweep --warm-ckpt rides this).
    restored = restore_checkpoint(ckpt_dir, step)
    donor = restored["sac"]
    sac = sac.replace(enc_params=donor["enc_params"],
                      actor_params=donor["actor_params"])
    del restored, donor
    return sac


def train_offline(agent: CHSAC_AF, npz_path: str, steps: int,
                  verbose: bool = False):
    """Pretrain ``agent`` from an offline npz dataset (reference schema).

    Loads the dataset into the agent's replay buffer (replacing its
    contents) and runs ``steps`` fused SAC updates.  Datasets smaller than
    the agent's warmup lower the warmup to the dataset size — call before
    any online training so the fused-update cache isn't built yet.
    Returns the last update's metrics dict (or None if the dataset is empty).
    """
    from .cmdp import COST_NAMES
    from .replay import load_offline_npz

    capacity = agent.replay.s0.shape[0]
    rb = load_offline_npz(npz_path, capacity, COST_NAMES,
                          n_dc=agent.cfg.n_dc, n_g=agent.cfg.n_g)
    got = (rb.s0.shape[1], rb.mask_dc.shape[1], rb.mask_g.shape[1])
    want = (agent.cfg.obs_dim, agent.cfg.n_dc, agent.cfg.n_g)
    if got != want:
        raise ValueError(
            f"offline dataset dims (obs_dim, n_dc, n_g)={got} do not match "
            f"the agent's {want}; rebuild the dataset with the matching "
            "fleet / --max-gpus-per-job")
    agent.replay = rb
    n_rows = int(agent.replay.size)
    if n_rows == 0:
        return None
    if n_rows < agent.warmup:
        if verbose:
            print(f"offline dataset has {n_rows} rows < warmup "
                  f"{agent.warmup}; lowering warmup")
        agent.warmup = n_rows
        agent._fused = {}  # fused programs capture warmup; rebuild
    metrics = None
    done = 0
    while done < steps:
        # fixed max_steps so every block reuses ONE fused program; the
        # n_train gate inside handles the final partial block
        m, n_done = agent.train_steps(steps - done, 256)
        if n_done == 0:
            break
        metrics, done = m, done + n_done
        if verbose and done % 1024 < 256:
            print(f"offline pretrain {done}/{steps} "
                  f"critic_loss={float(m['critic_loss']):.4f}")
    return metrics


def train_chsac(
    fleet: FleetSpec,
    params: SimParams,
    out_dir: Optional[str] = None,
    chunk_steps: int = 2048,
    max_chunks: int = 10_000,
    train_every_n: int = 1,
    max_train_steps_per_chunk: int = 256,
    agent: Optional[CHSAC_AF] = None,
    verbose: bool = False,
    ckpt_dir: Optional[str] = None,
    ckpt_every_chunks: int = 50,
    ckpt_keep: int = 0,
    resume: bool = True,
    on_chunk=None,
    timer=None,
    obs=None,
    shutdown=None,
):
    """Run a full chsac_af simulation with online training.

    Returns (final SimState, agent, history list of metric dicts).
    ``train_every_n`` trains one SAC step per n new transitions (reference
    schedule: 1), capped per chunk to bound host-loop latency.  With
    ``ckpt_dir`` the full pipeline (SAC learner, replay, sim state, PRNG)
    checkpoints every ``ckpt_every_chunks`` chunks and auto-resumes from the
    latest step when ``resume``.  ``on_chunk(chunk, state, history)`` runs
    after every chunk (long-horizon drivers flush partial metric history
    with it, so a killed run keeps its evidence).  ``obs`` is an optional
    :class:`~..obs.export.ObsConfig` (requires ``params.obs_enabled``):
    telemetry rows in the emission stream feed the streaming exporters
    and the run-health watchdog checks once per chunk, exactly like the
    non-RL ``run_simulation`` loop.

    Checkpoints commit atomically with a digest manifest
    (docs/checkpointing.md); resume walks the verified fallback chain —
    an uncommitted or corrupt newest step is skipped with a logged
    reason and the run restores the next older verified one instead of
    crashing.  ``ckpt_keep`` > 0 prunes the store to the newest N
    verified steps after every save (0 keeps everything); stale staging
    debris is swept either way.

    ``shutdown`` (a :class:`~..utils.shutdown.ShutdownFlag`): on
    SIGTERM/SIGINT the loop stops at the next chunk boundary, saves a
    checkpoint, flushes the exporters, and stamps ``run_summary.json``
    ``status="interrupted"``.  A :class:`~..obs.health.RunAbort`
    (watchdog trip in mode="raise", or a campaign divergence probe
    raised from ``on_chunk``) flushes the exporters, writes the
    ``status="aborted"`` summary, and saves a FORENSIC checkpoint under
    ``ckpt_dir/aborted`` (kept out of the ``step_*`` resume namespace)
    plus an ``abort_context.json`` (tripping probe, chunk index, chaos
    stage/reseed, params fingerprint) before re-raising — the bundle
    ``sim.replay.replay_abort`` / ``scripts/replay_abort.py`` re-execute
    deterministically.  The last healthy ``step_*`` checkpoint predates
    the tripping chunk by construction (aborts fire before the save).
    """
    assert params.algo == "chsac_af"
    if agent is None:
        agent = make_agent(fleet, params)
    engine = Engine(fleet, params, policy_apply=agent.policy_apply)
    state = init_state(jax.random.key(params.seed), fleet, params,
                       workload=engine.workload)
    start_chunk = 0
    csv_watermark = None
    if ckpt_dir and resume:
        from ..utils.checkpoint import fallback_steps, restore_checkpoint

        # verified fallback chain: walk newest-first, skipping (with a
        # logged reason) any step that is uncommitted or fails its
        # manifest digest check — a crash mid-save or bit rot on the
        # newest step degrades the resume to the previous one
        for step in fallback_steps(ckpt_dir):
            like = {"sac": agent.sac, "replay": agent.replay,
                    "key": agent.key, "sim": state,
                    "csv": _wm_like(params)}
            try:
                out = restore_checkpoint(ckpt_dir, step, like=like,
                                         verify=False)
            except (ValueError, KeyError, TypeError):
                # pre-watermark checkpoint layout (no "csv" subtree);
                # transient I/O errors (OSError) propagate untouched
                like.pop("csv")
                try:
                    out = restore_checkpoint(ckpt_dir, step, like=like,
                                             verify=False)
                except (ValueError, KeyError, TypeError) as e:
                    raise RuntimeError(
                        f"checkpoint {ckpt_dir} step {step} is structurally "
                        "incompatible with this version (the SimState/replay "
                        "pytree layout changed, e.g. SimState arrival-chain "
                        "fields or the replay ring's valid/n_seen fields); "
                        "delete the checkpoint dir or pass --no-resume to "
                        "start fresh"
                    ) from e
                out["csv"] = None
            agent.sac, agent.replay = out["sac"], out["replay"]
            agent.key, state = out["key"], out["sim"]
            if out["csv"] is not None:
                csv_watermark = {k: int(v) for k, v in out["csv"].items()}
            start_chunk = step + 1
            if verbose:
                print(f"resumed from {ckpt_dir} at chunk {step}")
            break
    writers = _open_writers(out_dir, fleet, start_chunk, csv_watermark,
                            params=params)
    run_log = _run_log(out_dir)
    history = []
    from ..obs.trace import PhaseTimer, sim_progress

    timer = PhaseTimer() if timer is None else timer
    sink = _open_sink(obs, fleet, params, state=state,
                      watermark=csv_watermark)
    status = "completed"
    chunk = start_chunk

    from ..utils.checkpoint import config_fingerprint

    fingerprint = config_fingerprint(fleet, params) if ckpt_dir else ""

    def save_ckpt(into=None):
        from ..utils.checkpoint import gc_checkpoints, save_checkpoint

        wm = _save_watermark(params, writers, sink)
        save_checkpoint(into or ckpt_dir, step=chunk,
                        metadata=_ckpt_metadata(fleet, params, fingerprint,
                                                chunk),
                        sac=agent.sac, replay=agent.replay, key=agent.key,
                        sim=state, csv=wm)
        if into is None:
            # retention + stale-staging sweep on the resume store only
            # (the forensic aborted/ bundle is never pruned)
            gc_checkpoints(ckpt_dir, keep=ckpt_keep or None)

    try:
        for chunk in range(start_chunk, max_chunks):
            with timer.phase("rollout", fence=lambda: state.t):
                state, emissions = engine.run_chunk(state, agent.sac,
                                                    n_steps=chunk_steps)
            with timer.phase("io"):
                if sink is not None:
                    # one shared host fetch for the CSV drain AND the
                    # exporters; the rl ingest below keeps the DEVICE
                    # leaves (round-tripping them through the host would
                    # cost more than the shared fetch saves)
                    host_em = jax.device_get(emissions)
                    drain_emissions(host_em, writers)
                    _log_preempt_notices(run_log, host_em)
                    sink.submit_host(host_em)
                else:
                    drain_emissions(emissions, writers)
                    _log_preempt_notices(run_log, emissions)
            if sink is not None:
                sink.check(np.asarray(state.telemetry.viol))
            n_new = int(np.asarray(emissions["rl"]["valid"]).sum())
            with timer.phase("ingest"):
                agent.ingest_chunk(emissions["rl"])
            n_want = min(n_new // max(train_every_n, 1),
                         max_train_steps_per_chunk)
            # one fused device program for the whole chunk's updates
            with timer.phase("train", fence=lambda: agent.sac.step):
                metrics, n_done = (
                    agent.train_steps(n_want, max_train_steps_per_chunk)
                    if n_want else (None, 0))
            if metrics is not None:
                history.append({k: np.asarray(v) for k, v in metrics.items()})
                _log_rl_chunk(run_log, chunk, float(state.t), metrics, n_done)
            if verbose:
                extra = (f"replay={int(agent.replay.size)} "
                         + (f"critic_loss={float(metrics['critic_loss']):.4f} "
                            f"lambda={np.asarray(metrics['lambda'])}"
                            if metrics is not None else "warming up"))
                print(sim_progress(float(state.t), params.duration, extra=extra))
            done = bool(state.done)
            # on_chunk BEFORE the checkpoint: a kill between the two then
            # re-runs (and re-reports) the gap chunks on resume instead of
            # leaving a permanent hole in the caller's flushed history
            if on_chunk is not None:
                on_chunk(chunk, state, history)
            stop = _interrupted(shutdown) and not done
            if ckpt_dir and (done or stop
                             or (chunk + 1) % ckpt_every_chunks == 0):
                save_ckpt()
            if done:
                break
            if stop:
                status = "interrupted"
                break
    except RunAbort as e:
        # deliberate run-health abort: flush exporters, stamp the
        # summary, save the forensic checkpoint + replayable abort
        # context — then let it unwind
        abort_dir = (os.path.join(ckpt_dir, ABORT_CKPT_SUBDIR)
                     if ckpt_dir else None)
        _abort_cleanup(
            sink=sink, state=state, out_dir=out_dir, algo=params.algo,
            fleet=fleet, timer=timer,
            save_fn=(lambda: save_ckpt(abort_dir)) if ckpt_dir else None,
            context_fn=((lambda: _write_abort_ctx(
                abort_dir, error=e, chunk=chunk, chunk_steps=chunk_steps,
                fleet=fleet, params=params,
                trees=["sac", "replay", "key", "sim", "csv"],
                train={"train_every_n": train_every_n,
                       "max_train_steps_per_chunk":
                           max_train_steps_per_chunk}))
                if ckpt_dir else None))
        raise
    except BaseException:
        # already unwinding (Ctrl-C mid-dispatch, train failure): stop
        # the exporter worker fast — drop its queue, swallow deferred
        # writer errors (same contract as run_simulation's CSV drain)
        if sink is not None:
            sink.close(abort=True)
        raise
    from ..obs.export import host_phase_seconds

    if sink is not None:
        sink.finalize(state, status=status,
                      host_phases=host_phase_seconds(timer))
    elif out_dir and status != "completed":
        from ..obs.export import write_status_summary

        write_status_summary(out_dir, algo=params.algo, fleet=fleet,
                             state=state, status=status,
                             host_phases=host_phase_seconds(timer))
    if verbose:
        print(timer.summary())
    return state, agent, history


def train_ppo(
    fleet: FleetSpec,
    params: SimParams,
    n_rollouts: int,
    out_dir: Optional[str] = None,
    chunk_steps: int = 2048,
    max_chunks: int = 10_000,
    verbose: bool = False,
    ckpt_dir: Optional[str] = None,
    ckpt_every_chunks: int = 50,
    ckpt_keep: int = 0,
    resume: bool = True,
    mesh=None,
    timer=None,
    obs=None,
    shutdown=None,
):
    """Mesh-sharded on-policy PPO driver for the CLI (--algo ppo).

    Same shape as :func:`train_chsac_distributed`: R vmapped worlds shard
    over the mesh, rollout 0's cluster/job stream writes the reference CSVs,
    the chunk's transition stream IS the training batch (no replay).
    ``obs`` (an ObsConfig) exports rollout 0's telemetry stream and runs
    the watchdog on rollout 0's probe counters.
    Returns (rollout-0 SimState view, trainer, history).
    """
    from ..parallel.mesh import make_mesh
    from ..parallel.rollout import PPOTrainer

    trainer = PPOTrainer(
        fleet, params, n_rollouts=n_rollouts,
        mesh=mesh if mesh is not None else make_mesh(),
        seed=params.seed,
        stream_rollout0=out_dir is not None or obs is not None)
    start_chunk = 0
    csv_watermark = None
    if ckpt_dir and resume:
        from ..utils.checkpoint import steps

        if steps(ckpt_dir):
            try:
                # trainer.restore walks the verified fallback chain —
                # a corrupt newest step degrades to the previous one
                step, extra = trainer.restore(
                    ckpt_dir, extra_like={"csv": _wm_like(params)})
            except FileNotFoundError:
                step = None  # every candidate corrupt: start fresh
            except (ValueError, KeyError, TypeError) as e:
                # structural pytree mismatch (transient I/O errors like
                # OSError propagate untouched — do NOT tell the user to
                # delete a healthy checkpoint over those)
                raise RuntimeError(
                    f"checkpoint {ckpt_dir} is structurally incompatible "
                    "with this trainer (it may have been written by a "
                    "chsac_af run or an older pytree layout); delete the "
                    "checkpoint dir or pass --no-resume to start fresh"
                ) from e
            if step is not None:
                csv_watermark = {k: int(v) for k, v in extra["csv"].items()}
                start_chunk = step + 1
                if verbose:
                    print(f"resumed {n_rollouts} ppo rollouts from "
                          f"{ckpt_dir} at chunk {step}")
    writers = _open_writers(out_dir, fleet, start_chunk, csv_watermark,
                            params=params)
    history = []
    from ..obs.trace import PhaseTimer, sim_progress

    timer = PhaseTimer() if timer is None else timer
    sink = _open_sink(obs, fleet, params, watermark=csv_watermark)
    if sink is not None:
        # baseline = rollout 0's (possibly checkpoint-restored) counters,
        # the same stream check() reads below
        sink.watchdog.prime(np.asarray(trainer.states.telemetry.viol[0]))
    status = "completed"
    chunk = start_chunk
    from ..utils.checkpoint import config_fingerprint, gc_checkpoints

    fingerprint = config_fingerprint(fleet, params) if ckpt_dir else ""
    try:
        for chunk in range(start_chunk, max_chunks):
            with timer.phase("rollout+train", fence=lambda: trainer.states.t):
                metrics = trainer.train_chunk(chunk_steps=chunk_steps)
            with timer.phase("io"):
                em0 = trainer.rollout0_emissions
                if em0 is not None and (writers is not None
                                        or sink is not None):
                    em0 = jax.device_get(em0)  # one shared host fetch
                    if writers is not None:
                        drain_emissions(em0, writers)
                    if sink is not None:
                        sink.submit_host(em0)
            if sink is not None:
                sink.check(np.asarray(trainer.states.telemetry.viol[0]))
            history.append({k: np.asarray(v) for k, v in metrics.items()})
            if verbose:
                t0_sim = float(np.asarray(trainer.states.t).min())
                extra = (f"events={int(metrics['n_events'])} "
                         f"loss={float(metrics['loss']):.4f} "
                         f"transitions={int(metrics['n_transitions'])}")
                print(sim_progress(t0_sim, params.duration, extra=extra))
            done = trainer.all_done
            stop = _interrupted(shutdown) and not done
            if ckpt_dir and (done or stop
                             or (chunk + 1) % ckpt_every_chunks == 0):
                wm = _save_watermark(params, writers, sink)
                trainer.save(ckpt_dir, step=chunk, csv=wm,
                             metadata=_ckpt_metadata(fleet, params,
                                                     fingerprint, chunk))
                gc_checkpoints(ckpt_dir, keep=ckpt_keep or None)
            if done:
                break
            if stop:
                status = "interrupted"
                break
    except RunAbort as e:
        abort_dir = (os.path.join(ckpt_dir, ABORT_CKPT_SUBDIR)
                     if ckpt_dir else None)
        _abort_cleanup(
            sink=sink, state=jax.tree.map(lambda a: a[0], trainer.states),
            out_dir=out_dir, algo="ppo", fleet=fleet, timer=timer,
            save_fn=((lambda: trainer.save(
                abort_dir, step=chunk,
                csv=_save_watermark(params, writers, sink),
                metadata=_ckpt_metadata(fleet, params, fingerprint, chunk)))
                if ckpt_dir else None),
            context_fn=((lambda: _write_abort_ctx(
                abort_dir, error=e, chunk=chunk, chunk_steps=chunk_steps,
                fleet=fleet, params=params,
                trees=["ppo", "states", "csv"]))
                if ckpt_dir else None))
        raise
    except BaseException:
        if sink is not None:
            sink.close(abort=True)
        raise
    if verbose:
        print(timer.summary())
    state0 = jax.tree.map(lambda a: a[0], trainer.states)
    from ..obs.export import host_phase_seconds

    if sink is not None:
        sink.finalize(state0, status=status,
                      host_phases=host_phase_seconds(timer))
    elif out_dir and status != "completed":
        from ..obs.export import write_status_summary

        write_status_summary(out_dir, algo="ppo", fleet=fleet, state=state0,
                             host_phases=host_phase_seconds(timer),
                             status=status)
    return state0, trainer, history


def train_chsac_distributed(
    fleet: FleetSpec,
    params: SimParams,
    n_rollouts: int,
    out_dir: Optional[str] = None,
    chunk_steps: int = 2048,
    max_chunks: int = 10_000,
    sac_steps_per_chunk: int = 8,
    verbose: bool = False,
    ckpt_dir: Optional[str] = None,
    ckpt_every_chunks: int = 50,
    ckpt_keep: int = 0,
    resume: bool = True,
    mesh=None,
    init_sac=None,
    timer=None,
    obs=None,
    shutdown=None,
):
    """Mesh-sharded chsac_af training driver for the CLI (--rollouts N).

    R vmapped worlds shard over the available devices (a 1-device mesh is
    fine); rollout 0's cluster/job stream is written to ``out_dir`` as the
    reference CSVs while all R worlds feed the sharded replay.  Checkpoints
    the full batched pipeline.  ``init_sac`` replaces the fresh learner
    state (e.g. one pretrained offline via :func:`train_offline`) before
    any chunk runs — a checkpoint resume still wins over it.  Returns
    (rollout-0 SimState view, trainer, history).
    """
    from ..parallel.mesh import make_mesh
    from ..parallel.rollout import DistributedTrainer

    assert params.algo == "chsac_af"
    trainer = DistributedTrainer(
        fleet, params, n_rollouts=n_rollouts,
        mesh=mesh if mesh is not None else make_mesh(),
        sac_steps_per_chunk=sac_steps_per_chunk,
        seed=params.seed,
        stream_rollout0=out_dir is not None or obs is not None)
    if init_sac is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        trainer.sac = jax.device_put(
            init_sac, NamedSharding(trainer.mesh, PartitionSpec()))
    start_chunk = 0
    csv_watermark = None
    if ckpt_dir and resume:
        from ..utils.checkpoint import steps

        if steps(ckpt_dir):
            try:
                # verified fallback chain inside trainer.restore: a
                # corrupt newest step degrades to the previous one
                step, extra = trainer.restore(
                    ckpt_dir, extra_like={"csv": _wm_like(params)})
            except FileNotFoundError:
                if verbose:
                    print(f"no restorable checkpoint in {ckpt_dir}; "
                          "starting fresh")
            except (ValueError, KeyError, TypeError) as e:
                # structural pytree mismatch — e.g. the checkpoint was
                # written under a different run shape (the csv watermark
                # subtree gains a "fault" leaf on fault-enabled runs, and
                # SimState gained FaultState) — start fresh like the
                # sibling trainers do rather than crash the run
                if verbose:
                    print(f"checkpoint mismatch in {ckpt_dir} ({e}); "
                          "starting fresh")
            else:
                csv_watermark = {k: int(v) for k, v in extra["csv"].items()}
                start_chunk = step + 1
                if verbose:
                    print(f"resumed {n_rollouts} rollouts from {ckpt_dir} "
                          f"at chunk {step}")
    writers = _open_writers(out_dir, fleet, start_chunk, csv_watermark,
                            params=params)
    run_log = _run_log(out_dir)
    history = []

    from ..obs.trace import PhaseTimer, sim_progress

    timer = PhaseTimer() if timer is None else timer
    sink = _open_sink(obs, fleet, params, watermark=csv_watermark)
    if sink is not None:
        # baseline = rollout 0's (possibly checkpoint-restored) counters,
        # the same stream check() reads below
        sink.watchdog.prime(np.asarray(trainer.states.telemetry.viol[0]))
    status = "completed"
    chunk = start_chunk
    from ..utils.checkpoint import config_fingerprint, gc_checkpoints

    fingerprint = config_fingerprint(fleet, params) if ckpt_dir else ""
    try:
        for chunk in range(start_chunk, max_chunks):
            with timer.phase("rollout+train", fence=lambda: trainer.states.t):
                metrics = trainer.train_chunk(chunk_steps=chunk_steps)
            with timer.phase("io"):
                em0 = trainer.rollout0_emissions
                if em0 is not None and (writers is not None
                                        or sink is not None):
                    em0 = jax.device_get(em0)  # one shared host fetch
                    if writers is not None:
                        drain_emissions(em0, writers)
                        _log_preempt_notices(run_log, em0)
                    if sink is not None:
                        sink.submit_host(em0)
            if sink is not None:
                sink.check(np.asarray(trainer.states.telemetry.viol[0]))
            history.append({k: np.asarray(v) for k, v in metrics.items()})
            if bool(metrics.get("warmed", True)):
                _log_rl_chunk(run_log, chunk,
                              float(np.asarray(trainer.states.t).min()),
                              metrics,
                              int(np.asarray(metrics.get("n_finished", 0))))
            if verbose:
                t0_sim = float(np.asarray(trainer.states.t).min())
                extra = (f"events={int(metrics['n_events'])} "
                         f"replay={int(metrics['replay_size'])} "
                         + (f"critic_loss={float(metrics['critic_loss']):.4f}"
                            if bool(metrics["warmed"]) else "warming up"))
                print(sim_progress(t0_sim, params.duration, extra=extra))
            done = trainer.all_done
            stop = _interrupted(shutdown) and not done
            if ckpt_dir and (done or stop
                             or (chunk + 1) % ckpt_every_chunks == 0):
                wm = _save_watermark(params, writers, sink)
                trainer.save(ckpt_dir, step=chunk, csv=wm,
                             metadata=_ckpt_metadata(fleet, params,
                                                     fingerprint, chunk))
                gc_checkpoints(ckpt_dir, keep=ckpt_keep or None)
            if done:
                break
            if stop:
                status = "interrupted"
                break
    except RunAbort as e:
        abort_dir = (os.path.join(ckpt_dir, ABORT_CKPT_SUBDIR)
                     if ckpt_dir else None)
        _abort_cleanup(
            sink=sink, state=jax.tree.map(lambda a: a[0], trainer.states),
            out_dir=out_dir, algo=params.algo, fleet=fleet, timer=timer,
            save_fn=((lambda: trainer.save(
                abort_dir, step=chunk,
                csv=_save_watermark(params, writers, sink),
                metadata=_ckpt_metadata(fleet, params, fingerprint, chunk)))
                if ckpt_dir else None),
            context_fn=((lambda: _write_abort_ctx(
                abort_dir, error=e, chunk=chunk, chunk_steps=chunk_steps,
                fleet=fleet, params=params,
                trees=["sac", "replay", "states", "key", "csv"]))
                if ckpt_dir else None))
        raise
    except BaseException:
        if sink is not None:
            sink.close(abort=True)
        raise
    if verbose:
        print(timer.summary())
    state0 = jax.tree.map(lambda a: a[0], trainer.states)
    from ..obs.export import host_phase_seconds

    if sink is not None:
        sink.finalize(state0, status=status,
                      host_phases=host_phase_seconds(timer))
    elif out_dir and status != "completed":
        from ..obs.export import write_status_summary

        write_status_summary(out_dir, algo=params.algo, fleet=fleet,
                             state=state0, status=status,
                             host_phases=host_phase_seconds(timer))
    return state0, trainer, history

"""Online CHSAC-AF training loop: scan chunks interleaved with SAC updates.

The reference trains one SAC step per job completion inside its Python event
loop (`/root/reference/simcore/simulator_paper_multi.py:757-810`).  Here the
simulator runs as jitted scan chunks; between chunks the chunk's transition
stream is scattered into the device replay buffer and the number of train
steps equals the number of newly-finished (valid) transitions — same
updates-per-experience schedule, but with both rollout and update compiled.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np

from ..models.structs import FleetSpec, SimParams
from ..sim.io import CSVWriters, drain_emissions
from ..sim.engine import Engine, init_state
from .agent import CHSAC_AF


def train_chsac(
    fleet: FleetSpec,
    params: SimParams,
    out_dir: Optional[str] = None,
    chunk_steps: int = 2048,
    max_chunks: int = 10_000,
    train_every_n: int = 1,
    max_train_steps_per_chunk: int = 256,
    agent: Optional[CHSAC_AF] = None,
    verbose: bool = False,
    ckpt_dir: Optional[str] = None,
    ckpt_every_chunks: int = 50,
    resume: bool = True,
):
    """Run a full chsac_af simulation with online training.

    Returns (final SimState, agent, history list of metric dicts).
    ``train_every_n`` trains one SAC step per n new transitions (reference
    schedule: 1), capped per chunk to bound host-loop latency.  With
    ``ckpt_dir`` the full pipeline (SAC learner, replay, sim state, PRNG)
    checkpoints every ``ckpt_every_chunks`` chunks and auto-resumes from the
    latest step when ``resume``.
    """
    assert params.algo == "chsac_af"
    if agent is None:
        agent = CHSAC_AF(
            obs_dim=params.obs_dim(fleet.n_dc),
            n_dc=fleet.n_dc,
            n_g_choices=params.max_gpus_per_job,
            sla_p99_ms=params.sla_p99_ms,
            power_cap=params.power_cap if params.power_cap > 0 else None,
            energy_budget_j=params.energy_budget_j,
            buffer_capacity=params.rl_buffer,
            batch=params.rl_batch,
            warmup=params.rl_warmup,
            seed=params.seed,
        )
    engine = Engine(fleet, params, policy_apply=agent.policy_apply)
    state = init_state(jax.random.key(params.seed), fleet, params)
    start_chunk = 0
    if ckpt_dir and resume:
        from ..utils.checkpoint import latest_step, restore_checkpoint

        step = latest_step(ckpt_dir)
        if step is not None:
            like = {"sac": agent.sac, "replay": agent.replay,
                    "key": agent.key, "sim": state}
            out = restore_checkpoint(ckpt_dir, step, like=like)
            agent.sac, agent.replay = out["sac"], out["replay"]
            agent.key, state = out["key"], out["sim"]
            start_chunk = step + 1
            if verbose:
                print(f"resumed from {ckpt_dir} at chunk {step}")
    # append on resume so the pre-crash CSV prefix isn't truncated
    writers = (CSVWriters(out_dir, fleet, append=start_chunk > 0)
               if out_dir else None)
    history = []

    for chunk in range(start_chunk, max_chunks):
        state, emissions = engine.run_chunk(state, agent.sac, n_steps=chunk_steps)
        drain_emissions(emissions, writers)
        n_new = int(np.asarray(emissions["rl"]["valid"]).sum())
        agent.ingest_chunk(emissions["rl"])
        n_train = min(n_new // max(train_every_n, 1), max_train_steps_per_chunk)
        metrics = None
        for _ in range(n_train):
            metrics = agent.train_step()
        if metrics is not None:
            history.append({k: np.asarray(v) for k, v in metrics.items()})
            if verbose:
                print(f"[chunk {chunk}] t={float(state.t):.0f}s "
                      f"replay={int(agent.replay.size)} "
                      f"critic_loss={float(metrics['critic_loss']):.4f} "
                      f"lambda={np.asarray(metrics['lambda'])}")
        done = bool(state.done)
        if ckpt_dir and (done or (chunk + 1) % ckpt_every_chunks == 0):
            from ..utils.checkpoint import save_checkpoint

            save_checkpoint(ckpt_dir, step=chunk, sac=agent.sac,
                            replay=agent.replay, key=agent.key, sim=state)
        if done:
            break
    return state, agent, history

"""Device-resident replay buffer: struct-of-arrays pytree + jax.random sampling.

Replaces the reference's Python-object ring buffer
(`/root/reference/simcore/rl/replay.py:26-67`) with preallocated device
arrays, so transition ingest (a masked scatter over a whole scan chunk) and
batch sampling never round-trip to the host.  Per-name cost tensors become
one stacked [**, n_costs] axis; the npz offline-dataset format of the
reference (`replay.py:74-95`) is preserved by `save_offline_npz` /
`load_offline_npz` with the same ``costs/<name>`` key convention.
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class ReplayState:
    """Ring buffer of capacity C (all leaves have leading axis C)."""

    s0: jnp.ndarray  # [C, obs_dim] f32
    s1: jnp.ndarray  # [C, obs_dim] f32
    a_dc: jnp.ndarray  # [C] int32
    a_g: jnp.ndarray  # [C] int32
    r: jnp.ndarray  # [C] f32
    costs: jnp.ndarray  # [C, n_costs] f32
    done: jnp.ndarray  # [C] f32 (1.0 = terminal; reference uses single-step episodes)
    mask_dc: jnp.ndarray  # [C, n_dc] bool — masks valid at s1 (for target policy)
    mask_g: jnp.ndarray  # [C, n_g] bool
    mask_dc0: jnp.ndarray  # [C, n_dc] bool — masks in force when the action was taken
    mask_g0: jnp.ndarray  # [C, n_g] bool
    ptr: jnp.ndarray  # int32 next write slot
    size: jnp.ndarray  # int32 count of valid rows (<= C)


def replay_init(capacity: int, obs_dim: int, n_dc: int, n_g: int,
                n_costs: int) -> ReplayState:
    return ReplayState(
        s0=jnp.zeros((capacity, obs_dim), jnp.float32),
        s1=jnp.zeros((capacity, obs_dim), jnp.float32),
        a_dc=jnp.zeros((capacity,), jnp.int32),
        a_g=jnp.zeros((capacity,), jnp.int32),
        r=jnp.zeros((capacity,), jnp.float32),
        costs=jnp.zeros((capacity, n_costs), jnp.float32),
        done=jnp.ones((capacity,), jnp.float32),
        mask_dc=jnp.zeros((capacity, n_dc), bool),
        mask_g=jnp.zeros((capacity, n_g), bool),
        mask_dc0=jnp.zeros((capacity, n_dc), bool),
        mask_g0=jnp.zeros((capacity, n_g), bool),
        ptr=jnp.int32(0),
        size=jnp.int32(0),
    )


def replay_add_chunk(rb: ReplayState, tr: Dict[str, jnp.ndarray]) -> ReplayState:
    """Scatter a chunk of transitions (leading axis N, validity mask) in.

    ``tr`` is the engine's per-step RL emission stack: keys
    {valid [N], s0, s1, a_dc, a_g, r, costs, mask_dc, mask_g}.  Invalid rows
    are routed to a scratch slot (index C, dropped by the ring wrap) so the
    whole ingest is one vectorized scatter — no host compaction.
    """
    C = rb.s0.shape[0]
    valid = tr["valid"]
    offs = jnp.cumsum(valid.astype(jnp.int32)) - 1  # position among valid rows
    n_new = jnp.maximum(0, offs[-1] + 1) if offs.shape[0] else jnp.int32(0)
    idx = jnp.where(valid, (rb.ptr + offs) % C, C)  # C = out-of-bounds drop

    def scat(buf, vals):
        return buf.at[idx].set(vals.astype(buf.dtype), mode="drop")

    ones = jnp.ones(valid.shape, jnp.float32)
    return rb.replace(
        s0=scat(rb.s0, tr["s0"]),
        s1=scat(rb.s1, tr["s1"]),
        a_dc=scat(rb.a_dc, tr["a_dc"]),
        a_g=scat(rb.a_g, tr["a_g"]),
        r=scat(rb.r, tr["r"]),
        costs=scat(rb.costs, tr["costs"]),
        done=scat(rb.done, tr.get("done", ones)),
        mask_dc=scat(rb.mask_dc, tr["mask_dc"]),
        mask_g=scat(rb.mask_g, tr["mask_g"]),
        mask_dc0=scat(rb.mask_dc0, tr.get("mask_dc0", tr["mask_dc"])),
        mask_g0=scat(rb.mask_g0, tr.get("mask_g0", tr["mask_g"])),
        ptr=(rb.ptr + n_new) % C,
        size=jnp.minimum(rb.size + n_new, C),
    )


def replay_sample(rb: ReplayState, key, batch: int) -> Dict[str, jnp.ndarray]:
    """Uniform sample over the valid prefix; returns a batch dict."""
    idx = jax.random.randint(key, (batch,), 0, jnp.maximum(rb.size, 1))
    take = lambda a: a[idx]  # noqa: E731
    return {
        "s0": take(rb.s0), "s1": take(rb.s1),
        "a_dc": take(rb.a_dc), "a_g": take(rb.a_g),
        "r": take(rb.r), "costs": take(rb.costs), "done": take(rb.done),
        "mask_dc": take(rb.mask_dc), "mask_g": take(rb.mask_g),
        "mask_dc0": take(rb.mask_dc0), "mask_g0": take(rb.mask_g0),
    }


# ---------------------------------------------------------------------------
# Offline dataset (reference npz schema: `rl/replay.py:74-95`)
# ---------------------------------------------------------------------------

def save_offline_npz(rb: ReplayState, path: str, cost_names: Sequence[str]) -> None:
    """Valid rows -> compressed npz with the reference's key convention."""
    n = int(rb.size)
    arrs = {
        "s0": np.asarray(rb.s0[:n]), "s1": np.asarray(rb.s1[:n]),
        "a_dc": np.asarray(rb.a_dc[:n]), "a_g": np.asarray(rb.a_g[:n]),
        "r": np.asarray(rb.r[:n]), "done": np.asarray(rb.done[:n]),
        "mask_dc": np.asarray(rb.mask_dc[:n]), "mask_g": np.asarray(rb.mask_g[:n]),
        "mask_dc0": np.asarray(rb.mask_dc0[:n]), "mask_g0": np.asarray(rb.mask_g0[:n]),
    }
    for i, name in enumerate(cost_names):
        arrs[f"costs/{name}"] = np.asarray(rb.costs[:n, i])
    np.savez_compressed(path, **arrs)


def load_offline_npz(path: str, capacity: int,
                     cost_names: Sequence[str]) -> ReplayState:
    """npz -> ReplayState (rows beyond ``capacity`` are truncated)."""
    with np.load(path) as z:
        n = min(int(z["r"].shape[0]), capacity)
        obs_dim = z["s0"].shape[1]
        rb = replay_init(capacity, obs_dim, z["mask_dc"].shape[1],
                         z["mask_g"].shape[1], len(cost_names))
        costs = np.stack([z[f"costs/{c}"][:n] for c in cost_names], axis=-1)
        return rb.replace(
            s0=rb.s0.at[:n].set(z["s0"][:n]),
            s1=rb.s1.at[:n].set(z["s1"][:n]),
            a_dc=rb.a_dc.at[:n].set(z["a_dc"][:n]),
            a_g=rb.a_g.at[:n].set(z["a_g"][:n]),
            r=rb.r.at[:n].set(z["r"][:n]),
            costs=rb.costs.at[:n].set(costs),
            done=rb.done.at[:n].set(z["done"][:n]),
            mask_dc=rb.mask_dc.at[:n].set(z["mask_dc"][:n]),
            mask_g=rb.mask_g.at[:n].set(z["mask_g"][:n]),
            mask_dc0=rb.mask_dc0.at[:n].set(
                z["mask_dc0"][:n] if "mask_dc0" in z else z["mask_dc"][:n]),
            mask_g0=rb.mask_g0.at[:n].set(
                z["mask_g0"][:n] if "mask_g0" in z else z["mask_g"][:n]),
            ptr=jnp.int32(n % capacity),
            size=jnp.int32(n),
        )

"""Device-resident replay buffer: struct-of-arrays pytree + jax.random sampling.

Replaces the reference's Python-object ring buffer
(`/root/reference/simcore/rl/replay.py:26-67`) with preallocated device
arrays, so transition ingest and batch sampling never round-trip to the
host.  Per-name cost tensors become one stacked [**, n_costs] axis; the npz
offline-dataset format follows the reference's ``costs/<name>`` key
convention (`replay.py:74-95`) but names the observation keys ``s0``/``s1``
where the reference uses ``s``/``s_next`` — `load_offline_npz` accepts
either spelling, so reference-written datasets load here; datasets written
by `save_offline_npz` use the s0/s1 spelling.

Ingest layout (TPU-first): a chunk of N rows is compacted valid-first with
one stable argsort + gather, then written as ONE contiguous
`dynamic_update_slice` at the ring pointer; the pointer advances by the
number of *valid* rows, so the invalid tail written past it is garbage that
the next chunk immediately overwrites.  A per-row ``valid`` bitmap rides
along and sampling draws uniformly over valid rows by inverse-CDF over the
bitmap.  This replaces the earlier per-row scatter (`.at[idx].set`): TPU
scatters serialize row-by-row, while sort/gather/slice-update all vectorize
(see docs/perf_notes.md, hypothesis 1).
"""

from __future__ import annotations

import os
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# Ingest layout, switchable for hardware A/B (read at import; jitted
# programs specialize on it): "slotring" = sort-compact + contiguous
# window writes (no scatter — TPU scatters serialize row-by-row);
# "scatter" = per-row compacted scatter (round-1 layout, cheaper on CPU).
# Both maintain identical valid/n_seen/size semantics and share sampling.
INGEST_MODE = os.environ.get("DCG_REPLAY_INGEST", "slotring")


@struct.dataclass
class ReplayState:
    """Ring buffer of capacity C (all row leaves have leading axis C).

    ``valid`` marks rows holding a real transition; ``size`` is the count of
    valid rows (== valid.sum(), maintained incrementally); ``ptr`` is the
    next write offset.  Rows in [ptr, ptr + last chunk's invalid tail) may
    be garbage with valid=False — they are never sampled.
    """

    s0: jnp.ndarray  # [C, obs_dim] f32
    s1: jnp.ndarray  # [C, obs_dim] f32
    a_dc: jnp.ndarray  # [C] int32
    a_g: jnp.ndarray  # [C] int32
    r: jnp.ndarray  # [C] f32
    costs: jnp.ndarray  # [C, n_costs] f32
    done: jnp.ndarray  # [C] f32 (1.0 = terminal; reference uses single-step episodes)
    mask_dc: jnp.ndarray  # [C, n_dc] bool — masks valid at s1 (for target policy)
    mask_g: jnp.ndarray  # [C, n_g] bool
    mask_dc0: jnp.ndarray  # [C, n_dc] bool — masks in force when the action was taken
    mask_g0: jnp.ndarray  # [C, n_g] bool
    valid: jnp.ndarray  # [C] bool — row holds a real transition
    ptr: jnp.ndarray  # int32 next write offset
    size: jnp.ndarray  # int32 count of valid rows (<= C)
    n_seen: jnp.ndarray  # int32 total valid rows ever ingested (monotone;
    # warmup gates use this, NOT size: the ring's garbage tails mean size
    # can plateau below capacity, which would deadlock a size-based warmup)


def replay_init(capacity: int, obs_dim: int, n_dc: int, n_g: int,
                n_costs: int) -> ReplayState:
    if capacity > (1 << 24):
        # replay_sample's inverse-CDF cumsum runs in float32: above 2^24
        # rows the running count can no longer increment, so later valid
        # rows would silently get zero sampling probability
        raise ValueError(
            f"replay capacity {capacity} exceeds 2^24; the float32 "
            "sampling CDF cannot index that many rows (and such a buffer "
            "would not fit device memory anyway) — lower --rl-buffer")
    return ReplayState(
        s0=jnp.zeros((capacity, obs_dim), jnp.float32),
        s1=jnp.zeros((capacity, obs_dim), jnp.float32),
        a_dc=jnp.zeros((capacity,), jnp.int32),
        a_g=jnp.zeros((capacity,), jnp.int32),
        r=jnp.zeros((capacity,), jnp.float32),
        costs=jnp.zeros((capacity, n_costs), jnp.float32),
        done=jnp.ones((capacity,), jnp.float32),
        mask_dc=jnp.zeros((capacity, n_dc), bool),
        mask_g=jnp.zeros((capacity, n_g), bool),
        mask_dc0=jnp.zeros((capacity, n_dc), bool),
        mask_g0=jnp.zeros((capacity, n_g), bool),
        valid=jnp.zeros((capacity,), bool),
        ptr=jnp.int32(0),
        size=jnp.int32(0),
        n_seen=jnp.int32(0),
    )


INGEST_WINDOW = 4096  # max rows per contiguous write window


def replay_add_chunk(rb: ReplayState, tr: Dict[str, jnp.ndarray],
                     max_window: int = INGEST_WINDOW) -> ReplayState:
    """Ingest a chunk of transitions (leading axis N, validity mask).

    ``tr`` is the engine's per-step RL emission stack: keys
    {valid [N], s0, s1, a_dc, a_g, r, costs, mask_dc, mask_g}.  With the
    default slot-ring layout each write window leaves a garbage tail of up
    to (window - n_valid) rows ahead of the pointer (overwritten by the
    next ingest), so large chunks are split into windows of at most
    ``max_window`` rows to bound the effective-capacity loss at
    ~2*max_window rows regardless of chunk size.  The window additionally
    scales down to capacity // 4 so a small ring (--rl-buffer close to the
    chunk size) keeps most of its rows live instead of becoming a
    permanent garbage tail.
    """
    C = rb.s0.shape[0]
    N = tr["valid"].shape[0]
    if N > C:  # keep the newest C rows (static slice; N, C are trace-time)
        tr = {k: v[N - C:] for k, v in tr.items()}
        N = C
    if INGEST_MODE == "scatter":
        return _add_scatter(rb, tr)
    w = min(max_window, N, max(1, C // 4))
    for k0 in range(0, N, w):
        sl = {k: v[k0:min(k0 + w, N)] for k, v in tr.items()}
        rb = _add_window(rb, sl)
    return rb


def _add_scatter(rb: ReplayState, tr: Dict[str, jnp.ndarray]) -> ReplayState:
    """Round-1 layout: compacted per-row scatter (rows land in insertion
    order at the ring pointer; invalid rows route to an out-of-bounds drop
    index).  Kept for hardware A/B against the slot-ring path."""
    C = rb.s0.shape[0]
    valid = tr["valid"].astype(bool)
    offs = jnp.cumsum(valid.astype(jnp.int32)) - 1
    n_new = jnp.maximum(0, offs[-1] + 1) if offs.shape[0] else jnp.int32(0)
    idx = jnp.where(valid, (rb.ptr + offs) % C, C)  # C = out-of-bounds drop

    def scat(buf, vals):
        return buf.at[idx].set(vals.astype(buf.dtype), mode="drop")

    ones = jnp.ones(valid.shape, jnp.float32)
    return rb.replace(
        s0=scat(rb.s0, tr["s0"]),
        s1=scat(rb.s1, tr["s1"]),
        a_dc=scat(rb.a_dc, tr["a_dc"]),
        a_g=scat(rb.a_g, tr["a_g"]),
        r=scat(rb.r, tr["r"]),
        costs=scat(rb.costs, tr["costs"]),
        done=scat(rb.done, tr.get("done", ones)),
        mask_dc=scat(rb.mask_dc, tr["mask_dc"]),
        mask_g=scat(rb.mask_g, tr["mask_g"]),
        mask_dc0=scat(rb.mask_dc0, tr.get("mask_dc0", tr["mask_dc"])),
        mask_g0=scat(rb.mask_g0, tr.get("mask_g0", tr["mask_g"])),
        valid=rb.valid.at[idx].set(True, mode="drop"),
        ptr=(rb.ptr + n_new) % C,
        size=jnp.minimum(rb.size + n_new, C),
        n_seen=rb.n_seen + n_new,
    )


def _add_window(rb: ReplayState, tr: Dict[str, jnp.ndarray]) -> ReplayState:
    C = rb.s0.shape[0]
    valid = tr["valid"].astype(bool)
    N = valid.shape[0]
    # valid-first permutation; stable => insertion order preserved
    perm = jnp.argsort(jnp.logical_not(valid), stable=True)
    n_new = jnp.sum(valid.astype(jnp.int32))
    sorted_valid = jnp.arange(N, dtype=jnp.int32) < n_new

    # ring placement: one contiguous window [start, start + N); wrap to 0
    # when the window would run off the end (rows left beyond the old ptr
    # keep their previous contents and flags)
    start = jnp.where(rb.ptr + N <= C, rb.ptr, 0)
    overwritten = jax.lax.dynamic_slice(rb.valid, (start,), (N,))
    n_lost = jnp.sum(overwritten.astype(jnp.int32))

    ones = jnp.ones((N,), jnp.float32)

    zero = jnp.zeros((), start.dtype)  # literal 0 would promote to int64
    # under jax_enable_x64 (the f64-clock runs) and dynamic_update_slice
    # requires all indices to share one integer type

    def put(buf, vals):
        vals = jnp.take(vals, perm, axis=0).astype(buf.dtype)
        return jax.lax.dynamic_update_slice(
            buf, vals, (start,) + (zero,) * (buf.ndim - 1))

    rb = rb.replace(
        s0=put(rb.s0, tr["s0"]),
        s1=put(rb.s1, tr["s1"]),
        a_dc=put(rb.a_dc, tr["a_dc"]),
        a_g=put(rb.a_g, tr["a_g"]),
        r=put(rb.r, tr["r"]),
        costs=put(rb.costs, tr["costs"]),
        done=put(rb.done, tr.get("done", ones)),
        mask_dc=put(rb.mask_dc, tr["mask_dc"]),
        mask_g=put(rb.mask_g, tr["mask_g"]),
        mask_dc0=put(rb.mask_dc0, tr.get("mask_dc0", tr["mask_dc"])),
        mask_g0=put(rb.mask_g0, tr.get("mask_g0", tr["mask_g"])),
        valid=jax.lax.dynamic_update_slice(rb.valid, sorted_valid, (start,)),
        ptr=start + n_new,
        size=rb.size - n_lost + n_new,
        n_seen=rb.n_seen + n_new,
    )
    return rb


def replay_sample(rb: ReplayState, key, batch: int) -> Dict[str, jnp.ndarray]:
    """Uniform sample over valid rows (inverse-CDF over the valid bitmap)."""
    cdf = jnp.cumsum(rb.valid.astype(jnp.float32))
    total = jnp.maximum(cdf[-1], 1.0)
    u = jax.random.uniform(key, (batch,)) * total
    idx = jnp.clip(jnp.searchsorted(cdf, u, side="right"), 0, rb.valid.shape[0] - 1)
    take = lambda a: a[idx]  # noqa: E731
    return {
        "s0": take(rb.s0), "s1": take(rb.s1),
        "a_dc": take(rb.a_dc), "a_g": take(rb.a_g),
        "r": take(rb.r), "costs": take(rb.costs), "done": take(rb.done),
        "mask_dc": take(rb.mask_dc), "mask_g": take(rb.mask_g),
        "mask_dc0": take(rb.mask_dc0), "mask_g0": take(rb.mask_g0),
    }


# ---------------------------------------------------------------------------
# Offline dataset (reference npz schema: `rl/replay.py:74-95`)
# ---------------------------------------------------------------------------

def save_offline_npz(rb: ReplayState, path: str, cost_names: Sequence[str]) -> None:
    """Valid rows -> compressed npz with the reference's key convention."""
    sel = np.flatnonzero(np.asarray(rb.valid))
    arrs = {
        "s0": np.asarray(rb.s0)[sel], "s1": np.asarray(rb.s1)[sel],
        "a_dc": np.asarray(rb.a_dc)[sel], "a_g": np.asarray(rb.a_g)[sel],
        "r": np.asarray(rb.r)[sel], "done": np.asarray(rb.done)[sel],
        "mask_dc": np.asarray(rb.mask_dc)[sel], "mask_g": np.asarray(rb.mask_g)[sel],
        "mask_dc0": np.asarray(rb.mask_dc0)[sel],
        "mask_g0": np.asarray(rb.mask_g0)[sel],
    }
    for i, name in enumerate(cost_names):
        arrs[f"costs/{name}"] = np.asarray(rb.costs)[sel, i]
    np.savez_compressed(path, **arrs)


def load_offline_npz(path: str, capacity: int, cost_names: Sequence[str],
                     n_dc: int | None = None,
                     n_g: int | None = None) -> ReplayState:
    """npz -> ReplayState (rows beyond ``capacity`` are truncated).

    Follows the reference schema's optionality: ``mask_dc``/``mask_g`` and
    ``costs/<name>`` keys may be absent (reference `replay.py:74-95` marks
    them optional).  Missing masks default to all-actions-valid — then the
    action-space sizes must be supplied via ``n_dc``/``n_g``; missing cost
    channels default to zero.
    """
    with np.load(path) as z:
        # the reference's loader spells the observation keys s/s_next
        # (reference replay.py:74-95); accept either dataset spelling
        s0 = z["s0"] if "s0" in z else z["s"]
        s1 = z["s1"] if "s1" in z else z["s_next"]
        n = min(int(z["r"].shape[0]), capacity)
        obs_dim = s0.shape[1]
        if "mask_dc" in z:
            n_dc = z["mask_dc"].shape[1]
        if "mask_g" in z:
            n_g = z["mask_g"].shape[1]
        if n_dc is None or n_g is None:
            raise ValueError(
                f"dataset {path} has no mask_dc/mask_g keys (legal in the "
                "reference schema) — pass n_dc= and n_g= so the all-valid "
                "default masks can be shaped")
        ones = np.ones((n,), np.float32)
        true_dc = np.ones((n, n_dc), bool)
        true_g = np.ones((n, n_g), bool)
        mask_dc = z["mask_dc"][:n] if "mask_dc" in z else true_dc
        mask_g = z["mask_g"][:n] if "mask_g" in z else true_g
        rb = replay_init(capacity, obs_dim, n_dc, n_g, len(cost_names))
        costs = np.stack(
            [z[f"costs/{c}"][:n] if f"costs/{c}" in z else np.zeros((n,), np.float32)
             for c in cost_names], axis=-1)
        return rb.replace(
            s0=rb.s0.at[:n].set(s0[:n]),
            s1=rb.s1.at[:n].set(s1[:n]),
            a_dc=rb.a_dc.at[:n].set(z["a_dc"][:n]),
            a_g=rb.a_g.at[:n].set(z["a_g"][:n]),
            r=rb.r.at[:n].set(z["r"][:n]),
            costs=rb.costs.at[:n].set(costs),
            done=rb.done.at[:n].set(z["done"][:n] if "done" in z else ones),
            mask_dc=rb.mask_dc.at[:n].set(mask_dc),
            mask_g=rb.mask_g.at[:n].set(mask_g),
            mask_dc0=rb.mask_dc0.at[:n].set(
                z["mask_dc0"][:n] if "mask_dc0" in z else mask_dc),
            mask_g0=rb.mask_g0.at[:n].set(
                z["mask_g0"][:n] if "mask_g0" in z else mask_g),
            valid=rb.valid.at[:n].set(True),
            ptr=jnp.int32(n % capacity),
            size=jnp.int32(n),
            n_seen=jnp.int32(n),
        )

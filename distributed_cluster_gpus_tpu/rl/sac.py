"""Distributional hybrid-action SAC (the CHSAC-AF learning core) in JAX.

TPU-native re-design of the reference torch implementation
(`/root/reference/simcore/rl/hybrid_sac.py:83-244` and
`rl/rl_energy_agent_adv_upgrade.py:28-53`): twin quantile critics trained
with the QR-DQN quantile Huber loss, a two-head masked-categorical actor
with learned temperature (target_entropy = -3), Polyak target sync
(tau = 0.005), and the Lagrangian effective reward folded in before the
critic target.  Differences from a torch port, by design:

* the entire update — replay sample, critic/actor/alpha Adam steps, Polyak
  sync, PID lambda update — is ONE jitted pure function
  ``sac_train_step(sac, replay, key) -> (sac, metrics)``; nothing crosses
  the host boundary between rollout chunks;
* actor and target terms marginalize over the full joint action set with a
  single batched MXU matmul (`QuantileCritic.all_actions`) instead of
  sampling, which is exact for discrete heads (the reference samples);
* gradients are optionally psum-ed over a named mesh axis, which is how the
  update runs data-parallel over ICI under shard_map (see parallel/).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from .cmdp import CMDPState, ConstraintSpec, cmdp_init, effective_reward, update_lagrange
from .nets import (HybridActor, MLPStateEncoder, QuantileCritic,
                   QuantileCriticHeads)
from .replay import ReplayState, replay_sample


@dataclasses.dataclass(frozen=True)
class SACConfig:
    """Static hyperparameters (reference defaults, `hybrid_sac.py:101-128`)."""

    obs_dim: int
    n_dc: int
    n_g: int
    n_quantiles: int = 32
    latent: int = 256
    gamma: float = 0.99
    tau: float = 0.005
    lr: float = 3e-4
    alpha_init: float = 0.2
    target_entropy: float = -3.0
    # Temperature-law note (deliberate divergence): the reference's
    # temp_loss = -(log_alpha * (logp + target)).mean() is DEGENERATE for
    # a discrete policy with target_entropy=-3 — logp - 3 is negative for
    # every possible policy, so alpha monotonically anneals to 0 and the
    # entropy term dies.  This port reads the target as an entropy floor
    # (loss = alpha * (H + target), i.e. chase H = 3 nats), which keeps
    # the mechanism alive — but under a constraint-saturated reward whose
    # Q-scale dwarfs alpha*H, entropy collapses anyway and alpha grows
    # without bound chasing it (observed in the canonical week run, see
    # docs/canonical_run.md).  ``alpha_max`` caps it (log-space clamp).
    #
    # Default 10.0 (round-4 decision, VERDICT item 5), defended by the
    # round-3 week trajectories (eval_results/week_chsac_history.json):
    # uncapped, alpha hit 2.3e7 chasing an entropy the saturated
    # advantage scale (|Q| ~ 1e7 from overload p99 violations) makes
    # unreachable — and once alpha is astronomical the actor objective is
    # ~pure entropy, i.e. a near-uniform policy (H jumps 0 -> 3.0 late in
    # that run), destroying the learned behavior in exactly the regime
    # being graded.  10.0 is (a) never binding in healthy regimes (the
    # 1-hour eval trajectories sit at alpha ~ 0.2-2), (b) the same bound
    # the reference gives its other adaptive multipliers (lambda clamp
    # [0, 10], `/root/reference/simcore/rl/cmdp_wrapper.py:7-12`), and
    # (c) large enough that alpha*H_max (~40) still dominates any healthy
    # advantage gap.  None reproduces the uncapped reference-shaped law.
    alpha_max: Optional[float] = 10.0
    grad_clip: float = 5.0
    batch: int = 256
    constraints: Tuple[ConstraintSpec, ...] = ()
    # "onehot" = reference-shaped critic taking one-hot actions as input
    # (`hybrid_sac.py:52-80`); "heads" = per-joint-action output heads —
    # ~14x cheaper exact marginalization, different parameterization
    critic_arch: str = "onehot"

    def __post_init__(self):
        assert self.constraints, "SACConfig needs at least one ConstraintSpec"
        assert self.critic_arch in ("onehot", "heads"), self.critic_arch
        assert self.alpha_max is None or self.alpha_max > 0, (
            f"alpha_max must be positive (log-space clamp), got {self.alpha_max}")


@struct.dataclass
class SACState:
    """All learned state: params, targets, optimizers, temperature, CMDP."""

    enc_params: dict
    actor_params: dict
    critic_params: dict
    target_critic_params: dict
    log_alpha: jnp.ndarray
    enc_opt: optax.OptState
    actor_opt: optax.OptState
    critic_opt: optax.OptState
    alpha_opt: optax.OptState
    cmdp: CMDPState
    step: jnp.ndarray  # int32 train steps taken


def _modules(cfg: SACConfig):
    enc = MLPStateEncoder(latent=cfg.latent)
    actor = HybridActor(n_dc=cfg.n_dc, n_g=cfg.n_g)
    cls = QuantileCriticHeads if cfg.critic_arch == "heads" else QuantileCritic
    critic = cls(n_dc=cfg.n_dc, n_g=cfg.n_g, n_quantiles=cfg.n_quantiles)
    return enc, actor, critic


def _tx(cfg: SACConfig):
    return optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                       optax.adam(cfg.lr))


def sac_init(cfg: SACConfig, key) -> SACState:
    enc, actor, critic = _modules(cfg)
    k_e, k_a, k_c = jax.random.split(key, 3)
    obs = jnp.zeros((1, cfg.obs_dim), jnp.float32)
    enc_p = enc.init(k_e, obs)
    lat = enc.apply(enc_p, obs)
    actor_p = actor.init(k_a, lat, jnp.ones((1, cfg.n_dc), bool),
                         jnp.ones((1, cfg.n_g), bool))
    critic_p = critic.init(k_c, lat, jnp.zeros((1,), jnp.int32),
                           jnp.zeros((1,), jnp.int32))
    tx = _tx(cfg)
    log_alpha = jnp.asarray(jnp.log(cfg.alpha_init), jnp.float32)
    return SACState(
        enc_params=enc_p, actor_params=actor_p, critic_params=critic_p,
        target_critic_params=critic_p,
        log_alpha=log_alpha,
        enc_opt=tx.init(enc_p), actor_opt=tx.init(actor_p),
        critic_opt=tx.init(critic_p),
        alpha_opt=_tx(cfg).init(log_alpha),
        cmdp=cmdp_init(cfg.constraints),
        step=jnp.int32(0),
    )


# ---------------------------------------------------------------------------
# Acting (runs inside the simulator scan)
# ---------------------------------------------------------------------------

def select_action(cfg: SACConfig, sac: SACState, obs, mask_dc, mask_g, key,
                  greedy: bool = False):
    """One masked categorical sample per head; obs is unbatched [obs_dim]."""
    enc, actor, _ = _modules(cfg)
    lat = enc.apply(sac.enc_params, obs[None])
    logp_dc, logp_g = actor.apply(sac.actor_params, lat, mask_dc[None], mask_g[None])
    if greedy:
        return (jnp.argmax(logp_dc[0]).astype(jnp.int32),
                jnp.argmax(logp_g[0]).astype(jnp.int32))
    k1, k2 = jax.random.split(key)
    a_dc = jax.random.categorical(k1, logp_dc[0])
    a_g = jax.random.categorical(k2, logp_g[0])
    return a_dc.astype(jnp.int32), a_g.astype(jnp.int32)


def make_policy_apply(cfg: SACConfig, greedy: bool = False):
    """Adapter matching the Engine's policy_apply signature."""

    def policy_apply(sac: SACState, obs, mask_dc, mask_g, key):
        return select_action(cfg, sac, obs, mask_dc, mask_g, key, greedy=greedy)

    return policy_apply


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------

def quantile_huber_loss(pred, target, taus, kappa: float = 1.0):
    """QR-DQN loss (`hybrid_sac.py:83-93`): pred [B, N], target [B, M]."""
    td = target[:, None, :] - pred[:, :, None]  # [B, N, M]
    abs_td = jnp.abs(td)
    huber = jnp.where(abs_td <= kappa, 0.5 * td**2, kappa * (abs_td - 0.5 * kappa))
    weight = jnp.abs(taus[None, :, None] - (td < 0).astype(jnp.float32))
    return jnp.mean(jnp.sum(jnp.mean(weight * huber, axis=2), axis=1))


def _joint_policy(cfg, actor_logp_dc, actor_logp_g):
    """Joint log-probs over the n_dc x n_g action set: [B, n_dc*n_g]."""
    return (actor_logp_dc[:, :, None] + actor_logp_g[:, None, :]).reshape(
        actor_logp_dc.shape[0], -1)


def sac_zero_metrics(cfg: SACConfig, sac: SACState):
    """Metrics pytree matching :func:`sac_train_step`'s, for skipped updates
    (warmup gating under `lax.cond` needs both branches structure-identical)."""
    z = jnp.float32(0.0)
    return {
        "critic_loss": z, "actor_loss": z, "alpha_loss": z,
        "alpha": jnp.exp(sac.log_alpha), "entropy": z,
        "q_mean": z, "r_eff_mean": z,
        "lambda": sac.cmdp.lam,
        "violation": jnp.zeros((len(cfg.constraints),), jnp.float32),
    }


def sac_train_step(cfg: SACConfig, sac: SACState, rb: ReplayState, key,
                   axis_name: Optional[str] = None):
    """One full CHSAC-AF update from a replay sample.

    When ``axis_name`` is set, gradients are psum-averaged over that mesh
    axis (data-parallel over ICI); each shard samples its own sub-batch.
    """
    enc, actor, critic = _modules(cfg)
    k_samp, k_dummy = jax.random.split(key)
    batch = replay_sample(rb, k_samp, cfg.batch)
    taus = (jnp.arange(cfg.n_quantiles, dtype=jnp.float32) + 0.5) / cfg.n_quantiles
    alpha = jnp.exp(sac.log_alpha)

    # Lagrangian effective reward (`rl_energy_agent_adv_upgrade.py:39-46`)
    targets = jnp.asarray([c.target for c in cfg.constraints], jnp.float32)
    r_eff = effective_reward(batch["r"], batch["costs"], sac.cmdp.lam, targets)

    # ---- critic target: exact marginalization over next actions ----
    lat1 = enc.apply(sac.enc_params, batch["s1"])
    logp_dc1, logp_g1 = actor.apply(sac.actor_params, lat1,
                                    batch["mask_dc"], batch["mask_g"])
    pi1 = jnp.exp(_joint_policy(cfg, logp_dc1, logp_g1))  # [B, A]
    logpi1 = _joint_policy(cfg, logp_dc1, logp_g1)
    q1_all = critic.apply(sac.target_critic_params, lat1, method=critic.all_actions)
    q1_min = jnp.min(q1_all, axis=1)  # [B, A, N]
    # E_{a~pi}[min twin quantiles - alpha log pi]
    soft_q1 = q1_min - alpha * logpi1[:, :, None]
    v1 = jnp.sum(pi1[:, :, None] * soft_q1, axis=1)  # [B, N]
    target_q = (r_eff[:, None]
                + cfg.gamma * (1.0 - batch["done"][:, None]) * v1)
    target_q = jax.lax.stop_gradient(target_q)

    # ---- critic loss ----
    def critic_loss_fn(params):
        lat0 = enc.apply(sac.enc_params, batch["s0"])
        q = critic.apply(params, lat0, batch["a_dc"], batch["a_g"])  # [B, 2, N]
        l1 = quantile_huber_loss(q[:, 0], target_q, taus)
        l2 = quantile_huber_loss(q[:, 1], target_q, taus)
        return l1 + l2, jnp.mean(q)

    (c_loss, q_mean), c_grads = jax.value_and_grad(
        critic_loss_fn, has_aux=True)(sac.critic_params)

    # ---- actor + encoder loss (exact expectation over actions, under the
    # masks that were in force when acting at s0) ----
    def actor_loss_fn(actor_params, enc_params):
        lat0 = enc.apply(enc_params, batch["s0"])
        logp_dc, logp_g = actor.apply(actor_params, lat0,
                                      batch["mask_dc0"], batch["mask_g0"])
        logpi = _joint_policy(cfg, logp_dc, logp_g)
        pi = jnp.exp(logpi)
        q_all = critic.apply(sac.critic_params, lat0, method=critic.all_actions)
        q_min = jnp.mean(jnp.min(q_all, axis=1), axis=-1)  # [B, A] mean over quantiles
        q_min = jax.lax.stop_gradient(q_min)
        ent = -jnp.sum(pi * logpi, axis=-1)  # [B]
        loss = -jnp.mean(jnp.sum(pi * q_min, axis=-1) + alpha * ent)
        return loss, ent

    (a_loss, ent), (a_grads, e_grads) = jax.value_and_grad(
        actor_loss_fn, has_aux=True, argnums=(0, 1))(sac.actor_params,
                                                     sac.enc_params)

    # ---- temperature loss (learned alpha, target_entropy -3) ----
    def alpha_loss_fn(log_alpha):
        return jnp.mean(jnp.exp(log_alpha)
                        * jax.lax.stop_gradient(ent + cfg.target_entropy))

    al_loss, al_grad = jax.value_and_grad(alpha_loss_fn)(sac.log_alpha)

    if axis_name is not None:
        c_grads, a_grads, e_grads, al_grad = jax.lax.pmean(
            (c_grads, a_grads, e_grads, al_grad), axis_name)

    tx = _tx(cfg)
    cu, c_opt = tx.update(c_grads, sac.critic_opt, sac.critic_params)
    au, a_opt = tx.update(a_grads, sac.actor_opt, sac.actor_params)
    eu, e_opt = tx.update(e_grads, sac.enc_opt, sac.enc_params)
    alu, al_opt = _tx(cfg).update(al_grad, sac.alpha_opt, sac.log_alpha)

    critic_params = optax.apply_updates(sac.critic_params, cu)
    new_target = jax.tree.map(
        lambda t, o: (1.0 - cfg.tau) * t + cfg.tau * o,
        sac.target_critic_params, critic_params)

    # ---- PID lambda update on batch-mean violation (pmean-ed over the
    # mesh axis so multipliers stay replicated) ----
    cmdp, viol = update_lagrange(sac.cmdp, cfg.constraints, batch["costs"],
                                 axis_name=axis_name)

    sac = sac.replace(
        enc_params=optax.apply_updates(sac.enc_params, eu),
        actor_params=optax.apply_updates(sac.actor_params, au),
        critic_params=critic_params,
        target_critic_params=new_target,
        log_alpha=(sac.log_alpha + alu if cfg.alpha_max is None else
                   jnp.minimum(sac.log_alpha + alu,
                               jnp.log(jnp.float32(cfg.alpha_max)))),
        enc_opt=e_opt, actor_opt=a_opt, critic_opt=c_opt, alpha_opt=al_opt,
        cmdp=cmdp,
        step=sac.step + 1,
    )
    metrics = {
        "critic_loss": c_loss, "actor_loss": a_loss, "alpha_loss": al_loss,
        "alpha": jnp.exp(sac.log_alpha), "entropy": jnp.mean(ent),
        "q_mean": q_mean, "r_eff_mean": jnp.mean(r_eff),
        "lambda": cmdp.lam, "violation": viol,
    }
    return sac, metrics

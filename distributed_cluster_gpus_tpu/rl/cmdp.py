"""PID-Lagrangian CMDP: effective reward shaping + multiplier update.

Parity with the reference (`/root/reference/simcore/rl/cmdp_wrapper.py:6-57`):
``r_eff = r - sum_i lambda_i * max(0, cost_i - target_i)`` and each lambda is
driven by a PID controller (kp=0.05, ki=0.01, kd=0) on the batch-mean
constraint violation, clamped to [0, lambda_max=10].  Here the multipliers
and PID integrator/derivative memories are a pure pytree so the whole update
lives inside the jitted train step.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
from flax import struct


@dataclasses.dataclass(frozen=True)
class ConstraintSpec:
    """Static constraint description (name + target + PID gains)."""

    name: str
    target: float
    kp: float = 0.05
    ki: float = 0.01
    kd: float = 0.0
    lambda_max: float = 10.0


@struct.dataclass
class CMDPState:
    """Per-constraint multipliers + PID memories ([n_costs] leaves)."""

    lam: jnp.ndarray  # [n_costs] f32 multipliers
    integral: jnp.ndarray  # [n_costs] f32 accumulated violation
    prev_err: jnp.ndarray  # [n_costs] f32 last violation (derivative term)


def cmdp_init(constraints: Sequence[ConstraintSpec]) -> CMDPState:
    n = len(constraints)
    return CMDPState(
        lam=jnp.zeros((n,), jnp.float32),
        integral=jnp.zeros((n,), jnp.float32),
        prev_err=jnp.zeros((n,), jnp.float32),
    )


def _gains(constraints: Sequence[ConstraintSpec]):
    tgt = jnp.asarray([c.target for c in constraints], jnp.float32)
    kp = jnp.asarray([c.kp for c in constraints], jnp.float32)
    ki = jnp.asarray([c.ki for c in constraints], jnp.float32)
    kd = jnp.asarray([c.kd for c in constraints], jnp.float32)
    lmax = jnp.asarray([c.lambda_max for c in constraints], jnp.float32)
    return tgt, kp, ki, kd, lmax


def effective_reward(r, costs, lam, targets) -> jnp.ndarray:
    """r_eff[b] = r[b] - sum_i lam[i] * max(0, costs[b, i] - target[i])."""
    viol = jnp.maximum(0.0, costs - targets[None, :])
    return r - jnp.sum(lam[None, :] * viol, axis=-1)


def update_lagrange(cmdp: CMDPState, constraints: Sequence[ConstraintSpec],
                    costs, axis_name: Optional[str] = None,
                    weights=None) -> Tuple[CMDPState, jnp.ndarray]:
    """PID step on batch-mean violation; returns (new state, mean violation).

    ``weights`` ([N] 0/1) restricts the mean to real transitions — the PPO
    path feeds the engine's full fixed-shape emission stream, where invalid
    rows carry live (but meaningless) cost features that must not count as
    violations.  With ``axis_name`` the violation is pmean-ed over the mesh
    axis so the multipliers stay bit-identical (replicated) on every shard.
    """
    tgt, kp, ki, kd, lmax = _gains(constraints)
    viol = jnp.maximum(0.0, costs - tgt[None, :])
    if weights is None:
        # equal-size shards: pmean of per-shard means IS the global mean
        err = jnp.mean(viol, axis=0)  # [n_costs]
        if axis_name is not None:
            import jax

            err = jax.lax.pmean(err, axis_name)
    else:
        # shards hold different valid-transition counts, so the global
        # weighted mean needs numerator and denominator summed separately
        # across the axis (a pmean of per-shard ratios would under-count
        # violations whenever some shards are still empty)
        num = jnp.sum(viol * weights[:, None], axis=0)
        den = jnp.sum(weights)
        if axis_name is not None:
            import jax

            num = jax.lax.psum(num, axis_name)
            den = jax.lax.psum(den, axis_name)
        err = num / jnp.maximum(den, 1.0)
    integral = cmdp.integral + err
    deriv = err - cmdp.prev_err
    lam = jnp.clip(kp * err + ki * integral + kd * deriv, 0.0, lmax)
    return cmdp.replace(lam=lam, integral=integral, prev_err=err), err


N_COSTS = 4  # fixed cost layout: [latency_p99_ms, power_W, gpu_over, energy_total_J]
COST_NAMES = ("latency_p99", "power", "gpu_over", "energy_total")


def default_constraints(sla_p99_ms: float = 500.0,
                        power_cap: Optional[float] = None,
                        energy_budget_j: Optional[float] = None,
                        ) -> Tuple[ConstraintSpec, ...]:
    """The reference CLI's constraint set (`run_sim_paper.py:107-114`).

    Order matters: it must match the engine's cost emission
    [latency_p99_ms, power_W, gpu_over, energy_total_J].  Optional
    constraints keep their slot with an effectively-infinite target so the
    cost layout (and every downstream array shape) is static.
    """
    big = 1e30
    return (
        ConstraintSpec("latency_p99", sla_p99_ms),
        ConstraintSpec("power", power_cap if power_cap and power_cap > 0 else big),
        ConstraintSpec("gpu_over", 0.0),
        ConstraintSpec("energy_total", energy_budget_j if energy_budget_j else big),
    )


def constraints_from_params(params) -> Tuple[ConstraintSpec, ...]:
    """Constraint set for a SimParams — single source for every trainer.

    The CMDP power target is ``power_cap_constraint`` when set, else
    ``power_cap`` (the reference CLI's fallback, `run_sim_paper.py:107-114`).
    """
    pcc = getattr(params, "power_cap_constraint", None)
    if pcc is None and params.power_cap > 0:
        pcc = params.power_cap
    return default_constraints(
        params.sla_p99_ms,
        pcc if pcc and pcc > 0 else None,
        params.energy_budget_j,
    )

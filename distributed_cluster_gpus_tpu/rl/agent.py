"""CHSAC-AF facade: wires encoder+actor+critic+CMDP+replay into one object.

Counterpart of `/root/reference/simcore/rl/rl_energy_agent_adv_upgrade.py:10-53`,
but holding only pure pytree state (SACState + ReplayState) plus the static
SACConfig — so the same object drives single-chip runs and mesh-sharded
training without code changes.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .cmdp import N_COSTS, default_constraints
from .replay import ReplayState, replay_add_chunk, replay_init
from .sac import (SACConfig, SACState, make_policy_apply, sac_init,
                  sac_train_step, sac_zero_metrics)


class CHSAC_AF:
    """Constrained Hybrid-action SAC with Action Feasibility masks."""

    def __init__(self, obs_dim: int, n_dc: int, n_g_choices: int,
                 sla_p99_ms: float = 500.0,
                 power_cap: Optional[float] = None,
                 energy_budget_j: Optional[float] = None,
                 buffer_capacity: int = 200_000,
                 batch: int = 256,
                 warmup: int = 1_000,
                 seed: int = 0,
                 axis_name: Optional[str] = None,
                 constraints=None,
                 critic_arch: str = "onehot"):
        self.cfg = SACConfig(
            obs_dim=obs_dim, n_dc=n_dc, n_g=n_g_choices, batch=batch,
            constraints=(constraints if constraints is not None else
                         default_constraints(sla_p99_ms, power_cap, energy_budget_j)),
            critic_arch=critic_arch,
        )
        self.warmup = warmup
        self.axis_name = axis_name
        # fold_in decorrelates the learner's key chain from the simulation's:
        # init_state also splits the raw key(seed), so splitting it here too
        # would make the agent's sampling keys collide with the sim's
        # per-event keys bit-for-bit (documented JAX key-reuse hazard)
        key = jax.random.fold_in(jax.random.key(seed), 0x7A31)
        self.key, k_init = jax.random.split(key)
        self.sac: SACState = sac_init(self.cfg, k_init)
        self.replay: ReplayState = replay_init(
            buffer_capacity, obs_dim, n_dc, n_g_choices, N_COSTS)
        self.policy_apply = make_policy_apply(self.cfg)
        self._train = jax.jit(
            lambda sac, rb, key: sac_train_step(self.cfg, sac, rb, key))
        self._ingest = jax.jit(replay_add_chunk)
        self._fused = {}  # max_steps -> jitted scan-of-updates program

    # -- rollout-side API ---------------------------------------------------

    def select_action(self, obs, mask_dc, mask_g) -> Dict[str, int]:
        """Host-convenience single action (the engine calls policy_apply
        directly inside the scan; this mirrors the reference API shape)."""
        self.key, k = jax.random.split(self.key)
        a_dc, a_g = self.policy_apply(self.sac, jnp.asarray(obs),
                                      jnp.asarray(mask_dc), jnp.asarray(mask_g), k)
        return {"dc": int(a_dc), "g": int(a_g)}

    def ingest_chunk(self, rl_emissions: Dict[str, jnp.ndarray]) -> int:
        """Scatter one scan chunk's RL transition stream into replay."""
        self.replay = self._ingest(self.replay, rl_emissions)
        return int(self.replay.size)

    # -- learning-side API --------------------------------------------------

    @property
    def ready(self) -> bool:
        # n_seen, not size: the ring's garbage tails can cap size below
        # capacity, but experience seen is monotone
        return int(self.replay.n_seen) >= self.warmup

    def train_step(self) -> Optional[Dict[str, jnp.ndarray]]:
        """One SAC+CMDP update if warmed up (reference `train_step` `:32-53`)."""
        if not self.ready:
            return None
        self.key, k = jax.random.split(self.key)
        self.sac, metrics = self._train(self.sac, self.replay, k)
        return metrics

    def _build_fused(self, max_steps: int):
        cfg, warmup = self.cfg, self.warmup

        def run(sac, rb, key, n_train):
            keys = jax.random.split(key, max_steps)
            idx = jnp.arange(max_steps)

            def body(carry, xk):
                i, k = xk
                sac_c, last = carry

                def train(op):
                    s, kk = op
                    return sac_train_step(cfg, s, rb, kk)

                def skip(op):
                    s, _ = op
                    return s, last

                do = (i < n_train) & (rb.n_seen >= warmup)
                sac_c, m = jax.lax.cond(do, train, skip, (sac_c, k))
                return (sac_c, m), do

            init = (sac, sac_zero_metrics(cfg, sac))
            (sac, last), dones = jax.lax.scan(body, init, (idx, keys))
            return sac, last, jnp.sum(dones)

        return jax.jit(run)

    def train_steps(self, n_train: int, max_steps: int = 256,
                    ) -> Tuple[Optional[Dict[str, jnp.ndarray]], int]:
        """Up to ``min(n_train, max_steps)`` SAC updates as ONE jitted scan.

        Replaces a Python loop of per-update device calls with a single
        device program per chunk (the updates-per-experience schedule is
        unchanged; warmup gating happens inside via `lax.cond`).  Returns
        (metrics of the last executed update or None, updates executed).
        """
        if max_steps not in self._fused:
            self._fused[max_steps] = self._build_fused(max_steps)
        self.key, k = jax.random.split(self.key)
        self.sac, metrics, n_done = self._fused[max_steps](
            self.sac, self.replay, k, jnp.int32(n_train))
        n_done = int(n_done)
        return (metrics if n_done > 0 else None), n_done

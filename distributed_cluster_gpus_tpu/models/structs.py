"""Core state pytrees and static world/run configuration.

This replaces the reference's mutable object graph (`simcore/models.py`:
`Job`/`PreemptedJob`/`DataCenter` dicts and Python lists) with
struct-of-arrays pytrees of static shape, so the whole simulator state can be
carried through `lax.scan`, vmapped over rollouts, and sharded with pjit:

* :class:`JobSlab` — fixed-capacity slab of jobs (replaces `running_jobs`
  dicts + unbounded `q_inf`/`q_train` lists; a `status` code plus a FIFO
  sequence number encode run/queue/transfer membership).
* :class:`DCArrays` — per-DC counters (busy GPUs, DC frequency, energy/util
  accumulators).
* :class:`SimState` — everything that changes during a run, including the
  arrival clocks (self-regenerating exponential/thinning clocks replace the
  reference's self-rescheduling arrival events) and the sliding latency
  windows used for p99 tracking.
* :class:`FleetSpec` — static world shape (fleet, coefficient tensors,
  precomputed WAN matrices, precomputed (n, f) energy grids). Held on the
  host as numpy and closed over by jit so XLA treats it as constants.
* :class:`SimParams` — static run shape (algo, durations, caps, RL hypers).
  A frozen hashable dataclass: passing a different SimParams re-specializes
  the compiled step, which is exactly the two-tier argparse/config split the
  reference has, but hashable for jit.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct

from ..fault.state import FaultParams, FaultState
from ..obs.metrics import TelemetryState
from ..ops.bandit import BanditState
from ..ops.physics import LatencyCoeffs, PowerCoeffs

if TYPE_CHECKING:  # annotation only: workload specs ride SimParams
    from ..workload.spec import WorkloadSpec

# --- algorithm codes (mirror the reference's --algo choices) ---
ALGO_DEFAULT = "default_policy"
ALGO_CAP_UNIFORM = "cap_uniform"
ALGO_CAP_GREEDY = "cap_greedy"
ALGO_JOINT_NF = "joint_nf"
ALGO_BANDIT = "bandit"
ALGO_CARBON_COST = "carbon_cost"
ALGO_ECO_ROUTE = "eco_route"
ALGO_CHSAC_AF = "chsac_af"
ALGO_DEBUG = "debug"

ALGO_CODES = (
    ALGO_DEFAULT,
    ALGO_CAP_UNIFORM,
    ALGO_CAP_GREEDY,
    ALGO_JOINT_NF,
    ALGO_BANDIT,
    ALGO_CARBON_COST,
    ALGO_ECO_ROUTE,
    ALGO_CHSAC_AF,
    ALGO_DEBUG,
)

N_JTYPE = 2  # 0 = inference, 1 = training


class JobStatus:
    """Job lifecycle codes stored in JobSlab.status."""

    EMPTY = 0
    XFER = 1  # in WAN transfer to its DC
    QUEUED = 2  # waiting in its DC queue
    RUNNING = 3
    PREEMPTED = 4


@struct.dataclass
class JobSlab:
    """Fixed-capacity struct-of-arrays job table ([J] leading axis).

    A slot is recycled as soon as its job finishes (job-log emission happens
    in the same step), so J only needs to bound the number of *concurrently
    live* jobs, not the total.
    """

    status: jnp.ndarray  # [J] int32 JobStatus
    jtype: jnp.ndarray  # [J] int32 (0 inf / 1 train)
    ingress: jnp.ndarray  # [J] int32
    dc: jnp.ndarray  # [J] int32
    seq: jnp.ndarray  # [J] int32 job id == FIFO order
    size: jnp.ndarray  # [J] f32 total work units
    units_done: jnp.ndarray  # [J] f32
    n: jnp.ndarray  # [J] int32 GPUs assigned
    f_idx: jnp.ndarray  # [J] int32 index into freq_levels
    t_ingress: jnp.ndarray  # [J] time of arrival at the ingress
    t_avail: jnp.ndarray  # [J] time WAN transfer completes
    t_start: jnp.ndarray  # [J] time started on GPUs
    net_lat_s: jnp.ndarray  # [J] f32 WAN propagation latency
    preempt_count: jnp.ndarray  # [J] int32
    preempt_t: jnp.ndarray  # [J] time of last preemption
    total_preempt_time: jnp.ndarray  # [J] f32
    # cached physics at the row's current (dc, jtype, n, f) — refreshed at
    # every site that changes a RUNNING job's n/f (start, cap controllers);
    # garbage for non-RUNNING rows (consumers guard on status)
    spu: jnp.ndarray  # [J] f32 seconds-per-unit T(n, f)
    watts: jnp.ndarray  # [J] f32 task power P(n, f)
    # RL traces (only meaningful under chsac_af)
    rl_obs0: jnp.ndarray  # [J, obs_dim] f32 obs at action-selection time
    rl_a_dc: jnp.ndarray  # [J] int32
    rl_a_g: jnp.ndarray  # [J] int32
    rl_mask_dc0: jnp.ndarray  # [J, n_dc] bool — action masks in force at s0
    rl_mask_g0: jnp.ndarray  # [J, n_g] bool
    rl_valid: jnp.ndarray  # [J] bool — has a stored (s0, a) trace


class QRec:
    """Field indices of a packed queue-ring record (see :class:`QueueRings`).

    One row is everything needed to re-materialize a waiting job into a
    JobSlab slot when GPUs free up.  RL traces (obs0/action/masks) are NOT
    stored: every path that starts a queued job re-selects its action and
    overwrites the slab's RL fields at commit time (engine `_policy_tail`
    drain / `_drain_queues`), so a queued job's stored trace would be dead
    weight.  All values ride one float row; ints (seq, ingress,
    preempt_count) are exact in f32 up to 2^24 — far beyond any realized
    job count (the canonical week is ~1e5 jobs).
    """

    SIZE = 0
    SEQ = 1
    INGRESS = 2
    T_INGRESS = 3
    T_AVAIL = 4
    NET_LAT_S = 5
    UNITS_DONE = 6
    T_START = 7
    PREEMPT_COUNT = 8
    PREEMPT_T = 9
    TOTAL_PREEMPT_TIME = 10
    N_FIELDS = 11


@struct.dataclass
class QueueRings:
    """Per-(DC, jtype) FIFO rings of jobs waiting for GPUs.

    The TPU answer to the reference's unbounded `q_inf`/`q_train` Python
    lists (`/root/reference/simcore/models.py:61-62`): waiting jobs leave
    the JobSlab entirely, so the per-step whole-slab ops (progress,
    physics, argmins) touch only *placed* work — the slab stays small and
    fast no matter how deep the backlog grows — while each ring push/pop
    is one dynamic row read/write of :data:`QRec.N_FIELDS` scalars and
    queue lengths are O(1) counter reads (`tail - head`).  Rings are FIFO
    by push order, which is exactly the reference's append/pop(0) order
    (jobs enter at WAN-transfer completion).  A full ring drops the
    arrival into `n_dropped` — size `queue_cap` to the workload (the CLIs
    auto-size from duration x arrival rate, making the default runs
    drop-free like the reference).
    """

    recs: jnp.ndarray  # [n_dc, N_JTYPE, Q, QRec.N_FIELDS] time-dtype rows
    head: jnp.ndarray  # [n_dc, N_JTYPE] int32 total pops (ring pos = head % Q)
    tail: jnp.ndarray  # [n_dc, N_JTYPE] int32 total pushes


@struct.dataclass
class DCArrays:
    """Per-DC dynamic counters ([n_dc] leading axis)."""

    busy: jnp.ndarray  # [n_dc] int32
    cur_f_idx: jnp.ndarray  # [n_dc] int32 DC-level DVFS setting
    energy_j: jnp.ndarray  # [n_dc] accumulated Joules
    util_gpu_time: jnp.ndarray  # [n_dc] sum busy*dt (GPU*s)
    acc_job_unit: jnp.ndarray  # [n_dc] accumulated processed units (log metric)


@struct.dataclass
class LatWindow:
    """Sliding window of the last W sojourn times per job type (p99 source)."""

    buf: jnp.ndarray  # [N_JTYPE, W] f32 seconds
    count: jnp.ndarray  # [N_JTYPE] int32 total ever pushed (capped use: min(count, W))
    ptr: jnp.ndarray  # [N_JTYPE] int32 ring pointer


@struct.dataclass
class SignalState:
    """Time-varying energy-signal accounting (workload/ subsystem).

    Carried in SimState only when the run's WorkloadSpec declares
    signal timelines (``SimParams.workload.signals``) — the signals-off
    program is untouched, same compile-gating contract as faults/obs.
    Accrued over the exact inter-event gaps next to the energy
    integral: ``cost_usd += (P * dt / 3.6e6) * price(t)`` and
    ``carbon_g += (P * dt / 3.6e6) * ci(dc, t)``.
    """

    cost_usd: jnp.ndarray  # [n_dc] f32 accumulated energy cost
    carbon_g: jnp.ndarray  # [n_dc] f32 accumulated gCO2


@struct.dataclass
class SimState:
    """Everything that changes during a run; one pytree, vmappable."""

    t: jnp.ndarray  # current simulated time (s)
    key: jnp.ndarray  # PRNG key
    jid_counter: jnp.ndarray  # int32 next job id
    started_accrual: jnp.ndarray  # bool — first event seen (energy/util baseline)
    t_first: jnp.ndarray  # time of first event (util_avg window start)
    dc: DCArrays
    jobs: JobSlab
    next_arrival: jnp.ndarray  # [n_ing, N_JTYPE] absolute times
    # dedicated workload PRNG chain: gap/size draws come from
    # fold_in(fold_in(arr_key, stream), arr_count[stream]) so the realized
    # arrival process is a pure function of the seed — identical across
    # algorithms (fair comparisons) and independent across rollouts
    arr_key: jnp.ndarray  # typed PRNG key, per-rollout workload base
    arr_count: jnp.ndarray  # [n_ing, N_JTYPE] int32 draws made per stream
    # workload-compiler fold carries (round 10, docs/workloads.md):
    # `arr_cum` is the per-stream cumulative Exp(1) sum at the cursor
    # (the left-fold carry of the inversion/rate-timeline generators)
    # and `arr_epoch` the stream's fixed first-arrival anchor — together
    # they make per-chunk pregeneration a pure function of (seed,
    # draw index), bit-identical across any chunking and superstep K
    arr_cum: jnp.ndarray  # [n_ing, N_JTYPE] tdtype
    arr_epoch: jnp.ndarray  # [n_ing, N_JTYPE] tdtype
    next_log_t: jnp.ndarray  # absolute time of next log tick
    lat: LatWindow
    bandit: BanditState
    queues: QueueRings
    # counters / accounting
    n_events: jnp.ndarray  # int32 events processed
    n_finished: jnp.ndarray  # [N_JTYPE] int32 completed jobs
    units_finished: jnp.ndarray  # [N_JTYPE] f32 total work units of completed jobs
    n_dropped: jnp.ndarray  # int32 arrivals dropped due to slab overflow
    done: jnp.ndarray  # bool — simulation reached end_time / drained
    # compiled fault timeline + degradation masks (None unless
    # SimParams.faults is set — the fault-free program is untouched)
    fault: Optional[FaultState] = None
    # in-graph telemetry accumulators (None unless SimParams.obs_enabled —
    # the obs-off program is untouched, same compile-gating as faults)
    telemetry: Optional[TelemetryState] = None
    # time-varying price/carbon accounting (None unless the workload
    # spec declares signal timelines — same compile-gating contract)
    signals: Optional[SignalState] = None


@dataclasses.dataclass(frozen=True)
class FleetSpec:
    """Static world shape. Numpy members; hashable by identity for jit closures.

    Built once by `configs.paper.build_fleet()`; the engine captures it in a
    closure so every array lands in the executable as a constant.
    """

    dc_names: Tuple[str, ...]
    ingress_names: Tuple[str, ...]
    gpu_names: Tuple[str, ...]  # per-DC GPU model name (display only)
    total_gpus: np.ndarray  # [n_dc] int32
    p_idle: np.ndarray  # [n_dc] f32 (per-GPU)
    p_peak: np.ndarray  # [n_dc] f32
    p_sleep: np.ndarray  # [n_dc] f32
    gpu_alpha: np.ndarray  # [n_dc] f32
    power_gating: np.ndarray  # [n_dc] bool
    freq_levels: np.ndarray  # [n_f] f32 shared DVFS ladder
    default_f_idx: int
    power: PowerCoeffs  # arrays [n_dc, N_JTYPE]
    latency: LatencyCoeffs  # arrays [n_dc, N_JTYPE]
    carbon: np.ndarray  # [n_dc] f32 gCO2/kWh (0 where unspecified)
    price_hourly: np.ndarray  # [24] f32 USD/kWh
    net_lat_s: np.ndarray  # [n_ing, n_dc] f32
    transfer_s: np.ndarray  # [n_ing, n_dc, N_JTYPE] f32
    # Precomputed (n, f) grids for the optimizers: [n_dc, N_JTYPE, n_max, n_f]
    T_grid: np.ndarray
    P_grid: np.ndarray
    E_grid: np.ndarray

    @property
    def n_dc(self) -> int:
        return len(self.dc_names)

    @property
    def n_ing(self) -> int:
        return len(self.ingress_names)

    @property
    def n_f(self) -> int:
        return int(self.freq_levels.shape[0])

    @property
    def n_max(self) -> int:
        return int(self.T_grid.shape[-2])

    def __hash__(self):  # identity hash: specs are built once and reused
        return id(self)

    def __eq__(self, other):
        return self is other


@dataclasses.dataclass(frozen=True)
class SimParams:
    """Static run shape — the argparse tier of the reference, hashable for jit."""

    algo: str = ALGO_DEFAULT
    duration: float = 180.0
    log_interval: float = 5.0
    # in-DC allocation policy (reference PolicyConfig)
    policy_name: str = "energy_aware"  # or "perf_first"
    max_gpus_per_job: int = 8
    inf_priority: bool = True
    # per-DC GPUs training jobs may never occupy (kept free for inference).
    # Live version of the reference's dead `policy.py:13` reserve_inf_gpus.
    reserve_inf_gpus: int = 0
    dvfs_low: float = 0.6
    dvfs_high: float = 1.0
    train_scale_out_low_freq: bool = True
    # arrivals.  The synthetic fields below describe the legacy
    # two-stream workload; setting ``workload`` (a WorkloadSpec:
    # replayed traces, rate timelines, diurnal/flash-crowd presets,
    # price/carbon signal timelines — workload/ subsystem,
    # docs/workloads.md) overrides them entirely.  Either way the
    # arrival streams compile through the same workload compiler into
    # pregenerated per-chunk tables (no in-step draws).
    inf_mode: str = "sinusoid"
    inf_rate: float = 6.0
    inf_amp: float = 0.6
    inf_period: float = 300.0
    trn_mode: str = "poisson"
    trn_rate: float = 0.3
    workload: Optional["WorkloadSpec"] = None
    # controllers
    power_cap: float = 0.0
    control_interval: float = 5.0
    cap_margin_w: float = 5.0
    eco_objective: str = "energy"  # energy | carbon | cost
    # weighted ingress routing (RouterPolicy made live — the reference's
    # `router.py:4-9` stores these weights but never consults them).  A
    # 5-tuple (w_latency, w_energy, w_carbon, w_cost, w_queue) replaces
    # random routing for the non-RL, non-eco_route algorithms; None keeps
    # the reference's uniform-random ingress routing.
    router_weights: Optional[Tuple[float, float, float, float, float]] = None
    # debug algo
    num_fixed_gpus: int = 1
    fixed_freq: Optional[float] = None
    # RL / constraints
    elastic_scaling: bool = False
    sla_p99_ms: float = 500.0
    energy_budget_j: Optional[float] = None
    # CMDP power target; None -> fall back to power_cap (reference
    # `run_sim_paper.py:107-114` wires these as separate knobs)
    power_cap_constraint: Optional[float] = None
    rl_buffer: int = 200_000
    rl_batch: int = 256
    rl_warmup: int = 1_000
    # Weight on the reward's energy term: r = -w*E_unit_kWh + 0.05/n.
    # 1.0 is the reference's fixed reward
    # (`simulator_paper_multi.py:764-774`); >1 is this framework's knob
    # for steering the agent toward the energy axis the heuristics win on
    # (docs/eval_r05.md) — an extension, not a ported behavior.
    rl_energy_weight: float = 1.0
    # "onehot" (reference-shaped critic) | "heads" (cheap marginalization)
    critic_arch: str = "onehot"
    # engine shape.  job_cap bounds concurrently *placed* jobs (in WAN
    # transfer / running / mid-preemption); waiting jobs live in the
    # per-(DC, jtype) queue rings of depth queue_cap (queue_mode "ring",
    # the default) or in the slab itself as QUEUED rows (queue_mode
    # "slab" — the pre-round-4 layout, kept for on-chip A/B: rings buy
    # O(1) queue ops + a small slab at the cost of one dynamic row
    # write per push).
    job_cap: int = 512
    queue_cap: int = 512
    queue_mode: str = "ring"  # "ring" | "slab"
    # superstep event coalescing (round 6; select-free since round 7):
    # each scan iteration applies the longest causally-commuting prefix
    # L in [1, K] of the pending events (earliest finishes / arrivals /
    # xfer-completions at pairwise-distinct DCs, all strictly before the
    # next control tick) through ONE unified branchless handler — no
    # fused-vs-singleton cond, so under vmap nothing executes twice; a
    # degenerate L=1 window reproduces the legacy singleton semantics
    # (log ticks, cap controllers, queue drains) through masked slot-0
    # paths, bit-for-bit (golden-tested against K=1).  1 (the default)
    # compiles the exact legacy one-event-per-step program —
    # bit-identical jaxpr.  Fault and signal-timeline runs are eligible
    # since round 12; the residue (chsac_af / bandit / weighted routing
    # — see engine.static_ineligibility for the reasons) always runs
    # singleton, and run_sim prints the reason.
    superstep_k: int = 1
    lat_window: int = 2048
    seed: int = 123
    time_dtype: str = "float32"  # "float64" for long-horizon fidelity runs
    # fault injection (fault/ subsystem): None compiles the exact
    # fault-free engine; a FaultParams spec adds the EV_FAULT event class,
    # capacity/derate/WAN masks, and the degraded-mode accounting
    faults: Optional[FaultParams] = None
    # in-graph telemetry (obs/ subsystem, docs/observability.md): False
    # compiles the exact pre-obs program; True carries a TelemetryState in
    # SimState (counters, EMAs, histograms, watchdog violation counters)
    # updated with masked writes every step and emits one flat metric
    # snapshot row per log tick for the streaming exporters
    obs_enabled: bool = False
    obs_ema_alpha: float = 0.05  # per-step EMA smoothing for power/ev-rate
    obs_qdepth_bins: int = 8  # log2 queue-depth histogram bins per DC

    def __post_init__(self):
        if self.algo not in ALGO_CODES:
            raise ValueError(f"unknown algo {self.algo!r}; choices: {ALGO_CODES}")
        if self.queue_mode not in ("ring", "slab"):
            raise ValueError(f"unknown queue_mode {self.queue_mode!r}")
        if self.policy_name not in ("energy_aware", "perf_first"):
            raise ValueError(f"unknown policy {self.policy_name!r}")
        if self.eco_objective not in ("energy", "carbon", "cost"):
            raise ValueError(f"unknown eco objective {self.eco_objective!r}")
        if not 1 <= self.superstep_k <= 16:
            raise ValueError(
                f"superstep_k={self.superstep_k} out of range [1, 16]: the "
                "fused handler unrolls K sub-steps, so very wide supersteps "
                "only bloat the program (diminishing window hit rate)")
        if not 0.0 < self.obs_ema_alpha <= 1.0:
            raise ValueError(
                f"obs_ema_alpha={self.obs_ema_alpha} outside (0, 1]")
        if self.obs_qdepth_bins < 2:
            raise ValueError(
                f"obs_qdepth_bins={self.obs_qdepth_bins} < 2: the queue "
                "histogram needs at least an empty bin and an overflow bin")
        if self.router_weights is not None and len(self.router_weights) != 5:
            raise ValueError(
                "router_weights needs exactly 5 values "
                "(w_latency, w_energy, w_carbon, w_cost, w_queue); got "
                f"{self.router_weights!r}")

    @property
    def tdtype(self):
        return jnp.float64 if self.time_dtype == "float64" else jnp.float32

    @property
    def signals_observed(self) -> bool:
        """True when the workload's price/carbon signals extend the RL obs."""
        return (self.workload is not None
                and self.workload.signals is not None
                and self.workload.signals.observe)

    def obs_dim(self, n_dc: int) -> int:
        """RL observation: [now] + per-DC [total, busy, free, cur_f, q_inf,
        q_trn]; workloads with observed signals append [price] + per-DC
        [carbon] (1 + n_dc more)."""
        base = 1 + 6 * n_dc
        if self.signals_observed:
            base += 1 + n_dc
        return base

"""Binary columnar artifact for sweep summary rows: shards + manifest.

The strict-JSON artifact stays the source of truth for resume and for
human/jq consumption, but rendering hundreds of thousands of JSON rows
becomes the wall at fleet scale — the same reason ``native/
csv_writer.cpp`` exists for the emission logs.  Rows here are *summary*
rows (tens of mixed-type fields), so the columnar sibling is pure
numpy: per-bucket shard files of contiguous column blobs written with
``ndarray.tofile`` (already fwrite-speed — the CSV writer's cost was
printf formatting, which a binary layout deletes outright) plus an
index manifest with per-shard SHA-256 digests in the checkpoint-
manifest style.

Shard layout (``dcg.sweep_columnar.v1``)::

    b"DCGCOL1\\n"                magic
    <u64 little-endian>          header length H
    <H bytes JSON>               {"schema", "n_rows",
                                  "columns": [{"name", "kind"}, ...]}
    per column, in header order:
      u8[n_rows]                 presence: 0 absent, 1 present, 2 null
      kind "i8" -> i64[n_rows]   (absent/null slots are 0)
      kind "f8" -> f64[n_rows]   (absent/null slots are 0.0; present
                                  NaN is a *real* NaN value — presence
                                  2 is JSON null, a different thing)
      kind "str"/"json" ->       u32[n_rows + 1] cumulative offsets +
                                 UTF-8 blob (json kind stores
                                 ``json.dumps`` per value — the exact
                                 round-trip fallback for bool/mixed
                                 columns)

Column kind selection preserves byte-fidelity of the summary JSON:
all-int columns store i64, all-float store f64 (IEEE doubles round-trip
``repr`` exactly), all-str store raw UTF-8, anything else (bools, or a
column mixing int and float across rows) falls back to per-value JSON
text.  ``read_rows(write_rows(rows))`` therefore reproduces the input
rows *byte-identically* under ``json.dumps`` — pinned by
tests/test_sweep.py's round-trip golden.

The manifest (``manifest.json``, ``dcg.sweep_manifest.v1``) indexes
shards: file name, row count, SHA-256.  Shard names derive from the
bucket's sorted cell keys, so a resumed grid re-writes the *same* shard
name for the same bucket instead of appending duplicates.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Sequence

import numpy as np

MAGIC = b"DCGCOL1\n"
SCHEMA = "dcg.sweep_columnar.v1"
MANIFEST_SCHEMA = "dcg.sweep_manifest.v1"
MANIFEST = "manifest.json"


def _column_kind(values: Sequence) -> str:
    """Pick the narrowest kind that reproduces every present value."""
    present = [v for v in values if v is not _ABSENT and v is not None]
    if not present:
        return "i8"
    if all(type(v) is int for v in present):
        return "i8"
    if all(type(v) is float for v in present):
        return "f8"
    if all(type(v) is str for v in present):
        return "str"
    return "json"


class _Absent:
    """Sentinel distinguishing a missing key from an explicit None."""

    def __repr__(self):
        return "<absent>"


_ABSENT = _Absent()


def write_shard(path: str, rows: Sequence[Dict]) -> None:
    """Write one shard of summary rows (atomic: tmp + rename)."""
    n = len(rows)
    names: List[str] = []
    for r in rows:
        for k in r:
            if k not in names:
                names.append(k)
    cols = []
    blobs = []
    for name in names:
        values = [r.get(name, _ABSENT) for r in rows]
        kind = _column_kind(values)
        presence = np.zeros(n, np.uint8)
        for i, v in enumerate(values):
            presence[i] = 0 if v is _ABSENT else (2 if v is None else 1)
        parts = [presence.tobytes()]
        if kind == "i8":
            arr = np.zeros(n, np.int64)
            for i, v in enumerate(values):
                if presence[i] == 1:
                    arr[i] = v
            parts.append(arr.tobytes())
        elif kind == "f8":
            arr = np.zeros(n, np.float64)
            for i, v in enumerate(values):
                if presence[i] == 1:
                    arr[i] = v
            parts.append(arr.tobytes())
        else:
            enc = [(v if kind == "str" else json.dumps(v)).encode()
                   if presence[i] == 1 else b""
                   for i, v in enumerate(values)]
            offs = np.zeros(n + 1, np.uint32)
            offs[1:] = np.cumsum(
                np.asarray([len(b) for b in enc], np.uint64)
            ).astype(np.uint32)
            parts.append(offs.tobytes())
            parts.append(b"".join(enc))
        cols.append({"name": name, "kind": kind})
        blobs.append(b"".join(parts))
    header = json.dumps({"schema": SCHEMA, "n_rows": n,
                         "columns": cols}, sort_keys=True).encode()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(np.uint64(len(header)).tobytes())
        f.write(header)
        for b in blobs:
            f.write(b)
    os.replace(tmp, path)


def read_shard(path: str) -> List[Dict]:
    """One shard file -> its summary rows (dicts, key order = column
    order = first-seen order at write time)."""
    with open(path, "rb") as f:
        buf = f.read()
    if buf[:len(MAGIC)] != MAGIC:
        raise ValueError(f"{path}: not a {SCHEMA} shard (bad magic)")
    pos = len(MAGIC)
    (hlen,) = np.frombuffer(buf, np.uint64, 1, pos)
    pos += 8
    header = json.loads(buf[pos:pos + int(hlen)].decode())
    if header.get("schema") != SCHEMA:
        raise ValueError(f"{path}: schema {header.get('schema')!r} != "
                         f"{SCHEMA}")
    pos += int(hlen)
    n = header["n_rows"]
    rows: List[Dict] = [{} for _ in range(n)]
    for col in header["columns"]:
        presence = np.frombuffer(buf, np.uint8, n, pos)
        pos += n
        kind = col["kind"]
        if kind in ("i8", "f8"):
            arr = np.frombuffer(buf, np.int64 if kind == "i8"
                                else np.float64, n, pos)
            pos += 8 * n
            for i in range(n):
                if presence[i] == 1:
                    rows[i][col["name"]] = (int(arr[i]) if kind == "i8"
                                            else float(arr[i]))
                elif presence[i] == 2:
                    rows[i][col["name"]] = None
        else:
            offs = np.frombuffer(buf, np.uint32, n + 1, pos)
            pos += 4 * (n + 1)
            blob = buf[pos:pos + int(offs[-1])]
            pos += int(offs[-1])
            for i in range(n):
                if presence[i] == 0:
                    continue
                if presence[i] == 2:
                    rows[i][col["name"]] = None
                    continue
                text = blob[offs[i]:offs[i + 1]].decode()
                rows[i][col["name"]] = (text if kind == "str"
                                        else json.loads(text))
    return rows


# ---------------------------------------------------------------------------
# sharded directory + manifest
# ---------------------------------------------------------------------------

def shard_name(keys: Sequence) -> str:
    """Content-derived shard file name from a bucket's cell keys —
    stable across resumed runs of the same grid."""
    digest = hashlib.sha256(
        json.dumps(sorted(str(k) for k in keys)).encode()).hexdigest()
    return f"shard_{digest[:12]}.dcgcol"


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_bucket(out_dir: str, keys: Sequence, rows: Sequence[Dict]) -> str:
    """Write one bucket's rows as a shard and re-index the manifest.

    Returns the shard file name.  Idempotent per bucket: the shard name
    is content-derived from the cell keys, so a resumed grid overwrites
    (byte-identically) rather than duplicating.
    """
    os.makedirs(out_dir, exist_ok=True)
    name = shard_name(keys)
    write_shard(os.path.join(out_dir, name), rows)
    mpath = os.path.join(out_dir, MANIFEST)
    manifest = {"schema": MANIFEST_SCHEMA, "shards": []}
    if os.path.exists(mpath):
        try:
            with open(mpath) as f:
                old = json.load(f)
            if old.get("schema") == MANIFEST_SCHEMA:
                manifest["shards"] = [s for s in old.get("shards", [])
                                      if s.get("file") != name]
        except (OSError, ValueError):
            pass  # rebuilt below from the shard being written
    manifest["shards"].append({
        "file": name, "rows": len(rows),
        "sha256": _sha256(os.path.join(out_dir, name))})
    manifest["shards"].sort(key=lambda s: s["file"])
    tmp = mpath + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, mpath)
    return name


def read_rows(out_dir: str, verify: bool = True) -> List[Dict]:
    """Every row of a sharded columnar artifact, manifest order.

    ``verify`` checks each shard's SHA-256 against the manifest (a
    truncated shard must fail loudly, not parse as fewer rows).
    """
    mpath = os.path.join(out_dir, MANIFEST)
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(f"{mpath}: schema {manifest.get('schema')!r} != "
                         f"{MANIFEST_SCHEMA}")
    rows: List[Dict] = []
    for s in manifest.get("shards", []):
        path = os.path.join(out_dir, s["file"])
        if verify:
            digest = _sha256(path)
            if digest != s.get("sha256"):
                raise ValueError(f"{path}: sha256 {digest[:12]}... does "
                                 f"not match the manifest")
        got = read_shard(path)
        if len(got) != s.get("rows"):
            raise ValueError(f"{path}: {len(got)} rows != manifest "
                             f"{s.get('rows')}")
        rows.extend(got)
    return rows

"""Declarative sweep-grid spec: the scenario axes of a chaos/workload sweep.

A :class:`SweepGrid` names the full cross product one capacity study
runs — chaos axis (stochastic outage rates OR curriculum presets at one
severity stage), workload preset, seeds, algorithms — plus the shared
run shape (fleet, duration, MTTR, obs).  It is the declarative input of
``scripts/sweep_grid.py`` and the delegation target of
``scripts/chaos_sweep.py``: both drivers enumerate the SAME cells from
the same spec, so the one-program grid compiler (`sweep/compiler.py`)
and the legacy serial loop are row-for-row interchangeable.

JSON spec files load through :func:`grid_from_dict` /
:func:`load_sweep_json` with strict unknown-key rejection, and
:func:`validate_grid` performs the range/consistency lint
(``scripts/sweep_grid.py --validate``) in the `validate_chaos.py`
style: one violation string per problem, never a traceback.

This module also owns the canonical :func:`cell_key` resume rule.  One
keying function serves both drivers and both axes, so a mixed artifact
(grid rows next to serial rows, rate rows next to preset rows) resumes
correctly no matter which driver wrote which row.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

#: every non-debug algorithm of the paper world (the default grid axis —
#: scripts/chaos_sweep.py re-exports this tuple)
ALL_ALGOS = ("default_policy", "cap_uniform", "cap_greedy", "joint_nf",
             "bandit", "carbon_cost", "eco_route", "chsac_af")

#: flag-less invocation defaults legacy artifact rows key under (the
#: PR 8 rule: a row banked before a field existed must resume a
#: flag-less re-run, and MUST NOT swallow a run that sets the flag)
DEFAULT_SEED = 123
DEFAULT_DURATION = 600.0
DEFAULT_MTTR = 300.0  # == configs.paper.CHAOS_MTTR_S (pinned by test)

_GRID_KEYS = {"axis", "rates", "presets", "stage", "algos", "seeds",
              "workload", "fleet", "duration", "mttr", "obs"}


@dataclasses.dataclass(frozen=True)
class SweepGrid:
    """One declarative sweep: scenario axes x shared run shape."""
    axis: str = "rates"               # "rates" | "presets"
    rates: Tuple[float, ...] = (0.0, 0.5, 1.0, 2.0)
    presets: Tuple[str, ...] = ()
    stage: int = 0                    # curriculum severity (presets axis)
    algos: Tuple[str, ...] = ALL_ALGOS
    seeds: Tuple[int, ...] = (DEFAULT_SEED,)
    workload: Optional[str] = None    # workload preset name or SPEC.json
    fleet: str = "paper"              # "paper" (config 4) | "duo" (--tiny)
    duration: float = DEFAULT_DURATION
    mttr: float = DEFAULT_MTTR
    obs: bool = False


@dataclasses.dataclass(frozen=True)
class SweepCell:
    """One grid point: the scenario parameters of a single summary row."""
    algo: str
    seed: int
    rate: Optional[float] = None
    preset: Optional[str] = None
    stage: Optional[int] = None
    workload: Optional[str] = None    # resolved workload *name* (row field)
    fleet: Optional[str] = None       # "duo" | None (paper, the legacy key)
    duration: float = DEFAULT_DURATION
    mttr: Optional[float] = None      # rate cells only

    def row_id(self) -> Dict:
        """The identity fields stamped onto this cell's summary row.

        Same shape the serial chaos_sweep loop writes — ``rate`` /
        ``preset`` always present (one of them None), optional fields
        only when set — so grid rows and serial rows are
        indistinguishable in the artifact.
        """
        d = {"rate": self.rate, "preset": self.preset, "algo": self.algo,
             "seed": self.seed, "duration": self.duration}
        if self.workload is not None:
            d["workload"] = self.workload
        if self.preset is not None:
            d["stage"] = self.stage
        if self.mttr is not None:
            d["mttr"] = self.mttr
        if self.fleet is not None:
            d["fleet"] = self.fleet
        return d


def cell_key(row: Dict) -> Tuple:
    """THE resume key of one sweep cell (grid and serial drivers alike).

    Rate cells carry ``rate``; preset cells carry ``preset`` (and write
    ``rate=None``) — one keying rule for both axes so a mixed artifact
    still resumes correctly.  The workload, curriculum stage, warm
    checkpoint, fleet, **seed, duration, and mttr** are all part of the
    key: re-running a sweep with any of them changed must COMPUTE those
    cells, not skip them because a same-named cell from another
    configuration is already banked.  Legacy rows without a field key
    as that field's flag-less default (None for the optional flags, the
    chaos_sweep argparse defaults for seed/duration/mttr) — so an old
    artifact still resumes a default invocation, and a ``--seed 7``
    re-run recomputes rather than skips (tests/test_sweep.py pins both
    directions).
    """
    axis = (f"preset:{row['preset']}" if row.get("preset") is not None
            else float(row["rate"]))
    mttr = row.get("mttr")
    return (axis, row["algo"], row.get("workload"), row.get("stage"),
            row.get("warm_ckpt"), row.get("fleet"),
            int(row.get("seed", DEFAULT_SEED)),
            float(row.get("duration", DEFAULT_DURATION)),
            float(DEFAULT_MTTR if mttr is None else mttr))


def load_done(path: str) -> Dict:
    """{cell_key: row} of a (possibly partial) sweep artifact."""
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            return {cell_key(r): r for r in json.load(f).get("rows", [])}
    except (json.JSONDecodeError, OSError, KeyError, TypeError):
        return {}


# ---------------------------------------------------------------------------
# spec file loading + lint
# ---------------------------------------------------------------------------

def grid_from_dict(d: Dict) -> SweepGrid:
    """Parse a spec dict into a SweepGrid; unknown keys are an error."""
    if not isinstance(d, dict):
        raise TypeError(f"sweep spec must be a JSON object, got "
                        f"{type(d).__name__}")
    unknown = set(d) - _GRID_KEYS
    if unknown:
        raise ValueError(f"unknown sweep spec key(s): {sorted(unknown)} "
                         f"(known: {sorted(_GRID_KEYS)})")
    kw = dict(d)
    for k in ("rates", "presets", "algos", "seeds"):
        if k in kw:
            v = kw[k]
            if not isinstance(v, (list, tuple)):
                raise TypeError(f"sweep spec {k!r} must be a list")
            kw[k] = tuple(v)
    if "axis" not in kw and kw.get("presets"):
        kw["axis"] = "presets"
    return SweepGrid(**kw)


def load_sweep_json(path: str) -> SweepGrid:
    with open(path) as f:
        return grid_from_dict(json.load(f))


def validate_grid(grid: SweepGrid, where: str = "<grid>") -> List[str]:
    """Schema/range lint; returns one violation string per problem."""
    from ..fault import CHAOS_PRESETS

    errs = []
    if grid.axis not in ("rates", "presets"):
        return [f"{where}: axis must be 'rates' or 'presets', got "
                f"{grid.axis!r}"]
    if grid.axis == "rates":
        if not grid.rates:
            errs.append(f"{where}: rates axis is empty")
        for r in grid.rates:
            if not isinstance(r, (int, float)) or r < 0:
                errs.append(f"{where}: rate {r!r} is not a >= 0 number")
    else:
        if not grid.presets:
            errs.append(f"{where}: presets axis is empty")
        known = set(CHAOS_PRESETS) | {"held_out"}
        for p in grid.presets:
            if p not in known:
                errs.append(f"{where}: unknown chaos preset {p!r} "
                            f"(known: {sorted(known)})")
        if not isinstance(grid.stage, int) or grid.stage < 0:
            errs.append(f"{where}: stage must be an int >= 0, got "
                        f"{grid.stage!r}")
    if not grid.algos:
        errs.append(f"{where}: algos is empty")
    for a in grid.algos:
        if a not in ALL_ALGOS:
            errs.append(f"{where}: unknown algo {a!r} (known: "
                        f"{list(ALL_ALGOS)})")
    if not grid.seeds:
        errs.append(f"{where}: seeds is empty")
    for s in grid.seeds:
        if not isinstance(s, int) or isinstance(s, bool):
            errs.append(f"{where}: seed {s!r} is not an int")
    if grid.fleet not in ("paper", "duo"):
        errs.append(f"{where}: fleet must be 'paper' or 'duo', got "
                    f"{grid.fleet!r}")
    if not grid.duration > 0:
        errs.append(f"{where}: duration must be > 0, got {grid.duration!r}")
    if not grid.mttr > 0:
        errs.append(f"{where}: mttr must be > 0, got {grid.mttr!r}")
    if grid.workload is not None:
        from ..workload import PRESETS

        if grid.workload not in PRESETS \
                and not os.path.exists(grid.workload):
            errs.append(f"{where}: workload {grid.workload!r} is neither "
                        f"a preset ({sorted(PRESETS)}) nor a spec file")
    return errs


# ---------------------------------------------------------------------------
# cell enumeration + scenario lowering (shared with chaos_sweep.py)
# ---------------------------------------------------------------------------

def expand_presets(names: Sequence[str]) -> List[str]:
    """Expand the ``held_out`` alias wherever it appears (not only alone)."""
    from ..fault import HELD_OUT_PRESETS

    out: List[str] = []
    for s in names:
        out.extend(HELD_OUT_PRESETS if s == "held_out" else [s])
    return out


def rate_fault_params(rates: Sequence[float], duration: float,
                      mttr: float) -> Dict[float, object]:
    """{rate: FaultParams} with ONE shared outage-window budget.

    Padding every rate's ``max_outages_per_dc`` to the sweep-wide max
    gives identical timeline shapes — identical HLO per algorithm class,
    so the persistent compile cache (serial driver) pays each compile
    once and the grid compiler folds all rates of an algorithm into one
    bucket.  Rate 0 is the enabled-but-empty golden baseline.  This is
    the one lowering rule both drivers share: chaos_sweep.py's serial
    loop and the grid compiler call this same function, so their
    FaultParams (and therefore their realized incident sequences) can
    never drift apart.
    """
    from ..configs.paper import build_chaos_faults
    from ..models import FaultParams

    pos = [r for r in rates if r > 0]
    k_max = (max(build_chaos_faults(r, duration, mttr).max_outages_per_dc
                 for r in pos) if pos else 2)
    out = {}
    for r in rates:
        if r > 0:
            out[r] = dataclasses.replace(
                build_chaos_faults(r, duration, mttr),
                max_outages_per_dc=k_max)
        else:
            out[r] = FaultParams()
    return out


def grid_cells(grid: SweepGrid) -> List[SweepCell]:
    """Enumerate the grid's cross product in the serial driver's order
    (axis-major, then algo, then seed) — resume keys are order-free, but
    matching the legacy order keeps mixed artifacts humanly diffable."""
    fleet_tag = "duo" if grid.fleet == "duo" else None
    wl = resolve_workload_name(grid)
    cells = []
    if grid.axis == "presets":
        for name in expand_presets(grid.presets):
            for algo in grid.algos:
                for seed in grid.seeds:
                    cells.append(SweepCell(
                        algo=algo, seed=seed, preset=name,
                        stage=grid.stage, workload=wl, fleet=fleet_tag,
                        duration=grid.duration))
    else:
        for rate in grid.rates:
            for algo in grid.algos:
                for seed in grid.seeds:
                    cells.append(SweepCell(
                        algo=algo, seed=seed, rate=float(rate),
                        workload=wl, fleet=fleet_tag,
                        duration=grid.duration, mttr=grid.mttr))
    return cells


def cell_fault_params(grid: SweepGrid, cells: Sequence[SweepCell]) -> Dict:
    """{cell: FaultParams} lowering the chaos axis per cell."""
    from ..fault import make_chaos_preset
    from ..models import FaultParams

    if grid.axis == "presets":
        by_name = {
            name: FaultParams(curriculum=make_chaos_preset(
                name, duration_s=grid.duration, stage=grid.stage))
            for name in {c.preset for c in cells}}
        return {c: by_name[c.preset] for c in cells}
    by_rate = rate_fault_params(sorted({c.rate for c in cells}),
                                grid.duration, grid.mttr)
    return {c: by_rate[c.rate] for c in cells}


def duo_base(duration: float):
    """The 2-DC duo-fleet sweep base (chaos_sweep.py --tiny / fleet
    "duo"): ONE builder so the CI world cannot drift between drivers."""
    from ..configs.paper import build_duo_fleet
    from ..models import SimParams

    base = SimParams(algo="default_policy", duration=duration,
                     log_interval=5.0, inf_mode="poisson", inf_rate=2.0,
                     trn_mode="poisson", trn_rate=0.1, job_cap=128,
                     queue_cap=512, rl_warmup=64, rl_batch=32)
    return {"fleet": build_duo_fleet(), "base": base}


def grid_base(grid: SweepGrid):
    """(fleet, SimParams base) for the grid — the same spec selection and
    seed/duration/workload stamping the serial driver performs."""
    from ..evaluation import baseline_config

    spec = (duo_base(grid.duration) if grid.fleet == "duo"
            else baseline_config(4, grid.duration))
    fleet, base = spec["fleet"], spec["base"]
    base = dataclasses.replace(base, seed=grid.seeds[0],
                               duration=grid.duration,
                               obs_enabled=grid.obs)
    if grid.workload is not None:
        base = dataclasses.replace(
            base, workload=resolve_workload(grid.workload, fleet,
                                            grid.duration))
    return fleet, base


def resolve_workload(name_or_path: str, fleet, duration: float):
    """Workload preset name or SPEC.json -> WorkloadSpec.

    The flash_crowd preset sizes its rate timeline to the run horizon —
    the exact rule chaos_sweep.py applies, factored here so the two
    drivers compile identical streams.
    """
    from ..workload import PRESETS, load_workload_json, make_preset

    if name_or_path in PRESETS:
        return (make_preset(name_or_path, fleet, horizon_s=duration)
                if name_or_path == "flash_crowd"
                else make_preset(name_or_path, fleet))
    return load_workload_json(name_or_path, fleet)


def resolve_workload_name(grid: SweepGrid) -> Optional[str]:
    """The workload *name* stamped on rows (spec files carry their own
    name field; resolving it needs no fleet)."""
    if grid.workload is None:
        return None
    from ..workload import PRESETS

    if grid.workload in PRESETS:
        return grid.workload
    from ..workload.spec import load_workload_json

    return load_workload_json(grid.workload, None).name

"""sweep/ — compile a scenario grid into one mesh-sharded program.

The declarative grid spec (`spec.SweepGrid`), the grid compiler that
buckets cells by compiled-program signature and runs each bucket as one
vmapped / shard_map-ready program (`compiler.run_grid`), and the binary
columnar artifact sibling (`columnar`).  docs/sweep.md is the contract;
scripts/sweep_grid.py is the CLI; scripts/chaos_sweep.py delegates here
when its grid is expressible.
"""

from .columnar import read_rows, write_bucket, write_shard, read_shard  # noqa: F401
from .compiler import (GRID_INEXPRESSIBLE, bucket_cells, expressible,  # noqa: F401
                       run_bucket, run_grid)
from .spec import (ALL_ALGOS, SweepCell, SweepGrid, cell_key,  # noqa: F401
                   grid_cells, grid_from_dict, load_done,
                   load_sweep_json, rate_fault_params, validate_grid)

"""Grid compiler: a scenario sweep as a handful of vmapped programs.

The serial sweep driver pays one Python dispatch sequence per cell —
and on CPU, dispatch (not FLOPs) is the wall (`bench_results/
attrib_r14.json`).  Everything a cell varies is already a pure function
of per-lane parameters: the workload realization and the fault timeline
are drawn from the per-lane PRNG at ``init_state``, and the engine's
compiled program reads NO ``FaultParams`` value at runtime (they lower
into ``FaultState`` timeline arrays inside ``SimState``).  So cells
that share a compiled-program signature can run as lanes of ONE
``jit(vmap(engine._run_chunk))`` loop — per-lane chaos, seeds, and
workload draws riding the lane axis — and the whole grid collapses to
one dispatch sequence per *bucket*.

Bucketing rule (``bucket_cells``): two cells share a bucket iff

* their ``SimParams`` agree on everything except ``seed`` and
  ``faults`` (algo family, workload spec, duration, obs, superstep_k,
  ... — every field the program specializes on),
* their ``static_ineligibility`` reasons agree (the round-12 residue:
  what fast-path programs the Engine compile-gates),
* their faults-enabled flag agrees (fault machinery is compile-gated),
* their initialized ``SimState`` pytrees have identical leaf
  shapes/dtypes (fault timeline budgets, workload carries — anything
  shape-bearing splits the bucket; the rate axis pre-pads its outage
  budgets via ``spec.rate_fault_params`` precisely so all rates of an
  algorithm land in one bucket).

Lane lowering contract: lane i's state is ``init_state(key(seed_i),
fleet, params_i, workload=engine.workload)`` — byte-for-byte the serial
driver's init (including the ``fold_in(key, 0x0FA17)`` fault
realization), stacked with ``jax.tree.map(jnp.stack, ...)``.  The init
itself runs vmapped over stacked per-seed keys within each
identical-params sub-group (the ``batched_init`` idiom — identical
values, one batched dispatch sequence instead of a per-lane eager
storm), and Engines + compiled runners cache across invocations so a
resumed or re-benched grid never re-uploads or retraces.  Stepping
a done lane is a no-op for every summary-relevant leaf (``t`` clamps to
``end``, accrual/counters gate on ``~done``), so lanes finishing at
different event counts run safely until the bucket drains.

On-device per-lane summary reduction: only the ``evaluation._summarize``
*inputs* leave the device — latency window, per-DC energy, counters,
fault/obs/signal accumulators, O(lat_window + n_dc) per lane — never
the O(job_cap + queue_cap) slab/ring leaves and never emission rows.
The final scalarization then reuses ``evaluation._summarize`` verbatim
on a lightweight view, which is what makes the grid's rows bit-identical
to serial ``run_algo`` rows (the correctness anchor
tests/test_sweep.py pins on both fleet shapes).

``run_grid`` adds resume + streaming: rows key by ``spec.cell_key``,
each completed bucket streams through an ``AsyncLineDrain`` worker that
atomically rewrites the strict-JSON artifact (and the columnar shard +
manifest, when enabled) — a SIGKILLed grid resumes per-bucket.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from types import SimpleNamespace
from typing import Dict, List, Optional, Sequence, Tuple

from . import columnar
from .spec import (SweepCell, SweepGrid, cell_fault_params, cell_key,
                   grid_base, grid_cells, load_done)

#: algorithms the one-program grid cannot express: chsac_af trains
#: online (a learner update between chunks — not a plain _run_chunk
#: loop), the same residue as the superstep's rl_policy_tail reason.
#: Drivers run these cells through the serial `run_algo` path instead.
GRID_INEXPRESSIBLE = ("chsac_af",)


def expressible(cell: SweepCell) -> bool:
    return cell.algo not in GRID_INEXPRESSIBLE


@dataclasses.dataclass
class Bucket:
    """One compiled program + its lanes."""
    engine: object                  # sim.engine.Engine (shared program)
    cells: List[SweepCell]
    params: List[object]            # per-lane SimParams
    states: List[object]            # per-lane SimState (unstacked)
    events: int = 0                 # total simulated events (run_bucket)

    @property
    def signature(self) -> str:
        p = self.params[0]
        return (f"{p.algo}/x{len(self.cells)}"
                + ("/obs" if p.obs_enabled else ""))


def cell_params(base, cell: SweepCell, faults) -> object:
    """SimParams of one cell — the serial driver's exact stamping."""
    return dataclasses.replace(base, algo=cell.algo, seed=cell.seed,
                               faults=faults)


#: Engines keyed by (fleet, level-1 bucket key).  A sweep driver (and
#: the bench probe) re-buckets the same grid many times; an Engine
#: carries the uploaded workload tables plus the compiled-runner cache
#: (`_sweep_run_cache`, see run_bucket) — rebuilding it per call would
#: re-upload and retrace every bucket program on every invocation.
_ENGINE_CACHE: Dict[Tuple, object] = {}


def bucket_cells(fleet, base, cells: Sequence[SweepCell],
                 fault_params: Dict) -> List[Bucket]:
    """Group cells by compiled-program signature and lower their lanes."""
    import jax
    import jax.numpy as jnp

    from ..sim.engine import Engine, init_state, static_ineligibility

    # level 1: everything the program specializes on except state shapes
    groups: Dict[Tuple, List[Tuple[SweepCell, object]]] = {}
    for cell in cells:
        p = cell_params(base, cell, fault_params[cell])
        inel = static_ineligibility(p)
        key = (dataclasses.replace(p, seed=0, faults=None),
               p.faults is not None and p.faults.enabled,
               tuple(sorted(inel["superstep"])),
               tuple(sorted(inel["planner"])))
        groups.setdefault(key, []).append((cell, p))

    buckets: List[Bucket] = []
    for gkey, members in groups.items():
        # ONE Engine per group: the compiled workload uploads once and
        # the program never reads FaultParams values, so the first
        # member's Engine serves every lane
        eng = _ENGINE_CACHE.get((fleet, gkey))
        if eng is None:
            eng = _ENGINE_CACHE[(fleet, gkey)] = Engine(fleet,
                                                        members[0][1])
        # lane init is vmapped per identical-params sub-group (same
        # SimParams, seeds vary) — the batched_init idiom.  On CPU the
        # per-lane eager init is the sweep's dominant per-cell cost
        # (hundreds of small op dispatches per lane), and vmap collapses
        # a sub-group to ONE batched dispatch sequence while producing
        # exactly the serial `init_state(key(seed_i))` values: the keys
        # are the exact per-seed keys (NOT batched_init's fold_in
        # chain), and vmap-of-pure-fn == stack-of-fn under the repo's
        # pinned-associativity discipline.
        by_p: Dict[object, List[Tuple[SweepCell, object]]] = {}
        for cell, p in members:
            by_p.setdefault(dataclasses.replace(p, seed=0),
                            []).append((cell, p))
        lane_states: Dict[SweepCell, object] = {}
        for sub in by_p.values():
            p0 = sub[0][1]
            keys = jnp.stack([jax.random.key(p.seed) for _, p in sub])
            sts = jax.vmap(
                lambda k, p0=p0: init_state(k, fleet, p0,
                                            workload=eng.workload))(keys)
            for i, (cell, _p) in enumerate(sub):
                lane_states[cell] = jax.tree.map(lambda x, i=i: x[i], sts)
        # level 2: split by state leaf signature (fault timeline
        # budgets, workload carries — anything shape-bearing)
        by_sig: Dict[Tuple, Bucket] = {}
        for cell, p in members:
            st = lane_states[cell]
            sig = tuple((tuple(leaf.shape), str(leaf.dtype))
                        for leaf in jax.tree.leaves(st))
            b = by_sig.get(sig)
            if b is None:
                b = by_sig[sig] = Bucket(engine=eng, cells=[], params=[],
                                         states=[])
            b.cells.append(cell)
            b.params.append(p)
            b.states.append(st)
        buckets.extend(by_sig.values())
    return buckets


# ---------------------------------------------------------------------------
# one bucket -> summary rows
# ---------------------------------------------------------------------------

def _summary_inputs(states):
    """The `evaluation._summarize` input sub-pytree, still stacked.

    Selection happens in-graph (it is the identity on the chosen
    leaves), so the big O(job_cap + queue_cap) slab/ring leaves never
    cross to the host — per lane only the latency window, per-DC
    energy, and the scalar accumulators transfer.
    """
    d = {"t": states.t, "n_events": states.n_events,
         "lat_buf": states.lat.buf, "lat_count": states.lat.count,
         "units_finished": states.units_finished,
         "energy_j": states.dc.energy_j,
         "n_finished": states.n_finished, "n_dropped": states.n_dropped}
    if states.fault is not None:
        fs = states.fault
        d["fault"] = {"downtime": fs.downtime, "n_outages": fs.n_outages,
                      "n_preempted": fs.n_preempted,
                      "n_migrated": fs.n_migrated,
                      "n_failed": fs.n_failed}
    if getattr(states, "telemetry", None) is not None:
        d["viol"] = states.telemetry.viol
    if getattr(states, "signals", None) is not None:
        d["cost_usd"] = states.signals.cost_usd
        d["carbon_g"] = states.signals.carbon_g
    return d


def _lane_view(host: Dict, i: int) -> SimpleNamespace:
    """Lane i of the fetched summary inputs as a state-shaped view the
    unmodified ``evaluation._summarize`` (and fault/obs/signal metric
    helpers) can read."""
    lane = SimpleNamespace(
        t=host["t"][i],
        lat=SimpleNamespace(buf=host["lat_buf"][i],
                            count=host["lat_count"][i]),
        dc=SimpleNamespace(energy_j=host["energy_j"][i]),
        units_finished=host["units_finished"][i],
        n_finished=host["n_finished"][i],
        n_dropped=host["n_dropped"][i],
        fault=None, telemetry=None, signals=None)
    if "fault" in host:
        lane.fault = SimpleNamespace(
            **{k: v[i] for k, v in host["fault"].items()})
    if "viol" in host:
        lane.telemetry = SimpleNamespace(viol=host["viol"][i])
    if "cost_usd" in host:
        lane.signals = SimpleNamespace(cost_usd=host["cost_usd"][i],
                                       carbon_g=host["carbon_g"][i])
    return lane


def run_bucket(bucket: Bucket, chunk_steps: int = 4096,
               mesh=None, max_chunks: int = 10_000) -> List[Dict]:
    """Run one bucket's lanes as ONE program; returns its summary rows."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..evaluation import _summarize

    eng = bucket.engine
    states = jax.tree.map(lambda *xs: jnp.stack(xs), *bucket.states)

    # compiled runners cache on the (cached) Engine, keyed by the
    # stacked-state leaf signature + chunk_steps + mesh: re-running the
    # same grid (resume, bench reps) must not retrace — jax.jit keyed
    # on a fresh lambda per call would.
    cache = getattr(eng, "_sweep_run_cache", None)
    if cache is None:
        cache = eng._sweep_run_cache = {}
    sig = tuple((tuple(leaf.shape), str(leaf.dtype))
                for leaf in jax.tree.leaves(states))
    sharded = (mesh is not None and len(bucket.cells) % mesh.size == 0
               and mesh.size > 1)
    run = cache.get((sig, chunk_steps, mesh if sharded else None))
    if run is None:
        def chunk(st):
            return eng._run_chunk(st, None, chunk_steps)[0]

        vrun = jax.vmap(chunk)
        if sharded:
            # ('dcn','rollout')-mesh shard_map: lanes split across
            # devices, per-lane programs stay independent (no
            # collectives) — the engine_shard_parity discipline,
            # applied to the grid
            from ..parallel.mesh import batch_pspec, shard_map_compat

            spec = batch_pspec(mesh)
            run = jax.jit(shard_map_compat(vrun, mesh=mesh,
                                           in_specs=(spec,),
                                           out_specs=spec),
                          donate_argnums=0)
        else:
            run = jax.jit(vrun, donate_argnums=0)
        cache[(sig, chunk_steps, mesh if sharded else None)] = run
    if sharded:
        from ..parallel.mesh import rollout_sharding

        states = jax.device_put(states, rollout_sharding(mesh))

    n = 0
    while not bool(np.asarray(states.done).all()):
        states = run(states)
        n += 1
        if n >= max_chunks:
            raise RuntimeError(
                f"bucket {bucket.signature}: {max_chunks} chunks without "
                f"draining — duration/chunk_steps mismatch?")

    host = jax.device_get(_summary_inputs(states))
    # total simulated events across lanes (n_events gates on ~done, so
    # overrun chunks add nothing) — the bench probe's ev/s numerator
    bucket.events = int(np.sum(host["n_events"]))
    rows = []
    for i, cell in enumerate(bucket.cells):
        s = _summarize(cell.algo, eng.fleet, _lane_view(host, i))
        row = s.row()
        row.update(cell.row_id())
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# the full grid: resume + streaming artifact
# ---------------------------------------------------------------------------

def run_grid(grid: SweepGrid, json_path: str, chunk_steps: int = 4096,
             columnar_dir: Optional[str] = None, mesh=None,
             note: Optional[str] = None, verbose: bool = True,
             serial: bool = False) -> Dict:
    """Run every not-yet-banked cell of ``grid``; stream the artifact.

    Returns ``{"rows": all rows, "ran": n_new, "buckets": n_buckets,
    "serial_cells": n_inexpressible}``.  Cells whose ``cell_key`` is
    already in ``json_path`` are skipped (per-bucket resume); grid-
    inexpressible cells (chsac_af's online training) run through the
    serial ``run_algo`` path into the same artifact.  ``serial=True``
    forces every cell down the serial path (the A/B reference arm).

    Streaming: each completed bucket submits one snapshot to an
    ``AsyncLineDrain`` worker that atomically rewrites the strict-JSON
    artifact (and columnar shard + manifest) off the hot loop — FIFO,
    bounded, errors re-raised on the next submit.

    ``DCG_SWEEP_TEST_KILL_AFTER=<n>`` (test hook) SIGKILLs the process
    after n buckets have been *flushed* — the resume test's
    deterministic mid-grid crash.
    """
    from ..sim.io import AsyncLineDrain
    from ..utils.jsonio import clean_nan, dump_json_atomic

    fleet, base = grid_base(grid)
    cells = grid_cells(grid)
    fp = cell_fault_params(grid, cells)
    done = load_done(json_path)

    todo, skipped = [], 0
    for cell in cells:
        if cell_key(cell.row_id()) in done:
            skipped += 1
            if verbose:
                axis = (f"preset={cell.preset}" if cell.preset is not None
                        else f"rate={cell.rate}")
                print(f"skip {axis} {cell.algo} seed={cell.seed} (done)")
        else:
            todo.append(cell)

    kill_after = int(os.environ.get("DCG_SWEEP_TEST_KILL_AFTER", 0))
    flushed = [0]

    def write_artifact(snapshot):
        doc = {"note": note or "sweep grid", "rows": snapshot["rows"]}
        dump_json_atomic(json_path, doc)
        if columnar_dir and snapshot.get("bucket") is not None:
            # same clean_nan lowering as the strict-JSON write: the two
            # artifacts must carry identical values (a NaN p99 from a
            # short run is null in both, not NaN in one)
            columnar.write_bucket(columnar_dir, snapshot["bucket"],
                                  clean_nan(snapshot["bucket_rows"]))
        flushed[0] += 1
        if kill_after and flushed[0] >= kill_after:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)

    os.makedirs(os.path.dirname(json_path) or ".", exist_ok=True)
    drain = AsyncLineDrain(write_artifact, maxsize=2,
                           name="sweep artifact drain")
    ran = 0
    n_serial = 0
    buckets: List[Bucket] = []
    try:
        grid_cells_todo = ([c for c in todo if expressible(c)]
                           if not serial else [])
        serial_cells = [c for c in todo if c not in grid_cells_todo]

        if grid_cells_todo:
            buckets = bucket_cells(fleet, base, grid_cells_todo, fp)
            if verbose:
                print(f"grid: {len(grid_cells_todo)} cell(s) in "
                      f"{len(buckets)} bucket(s) "
                      f"({skipped} already banked)")
            for b in buckets:
                rows = run_bucket(b, chunk_steps=chunk_steps, mesh=mesh)
                keys = []
                for row in rows:
                    done[cell_key(row)] = row
                    keys.append(cell_key(row))
                    ran += 1
                    if verbose:
                        _print_row(row)
                drain.submit({"rows": list(done.values()),
                              "bucket": keys, "bucket_rows": rows})

        for cell in serial_cells:
            row = _run_serial_cell(fleet, base, cell, fp[cell],
                                   chunk_steps)
            done[cell_key(row)] = row
            ran += 1
            n_serial += 1
            if verbose:
                _print_row(row)
            drain.submit({"rows": list(done.values()),
                          "bucket": [cell_key(row)],
                          "bucket_rows": [row]})
        drain.submit({"rows": list(done.values()), "bucket": None})
    except BaseException:
        drain.close(abort=True)
        raise
    drain.close()
    return {"rows": list(done.values()), "ran": ran,
            "buckets": len(buckets), "serial_cells": n_serial,
            "skipped": skipped}


def _run_serial_cell(fleet, base, cell: SweepCell, faults,
                     chunk_steps: int) -> Dict:
    """One grid-inexpressible cell through the serial run_algo path."""
    from ..evaluation import run_algo

    p = cell_params(base, cell, faults)
    row = run_algo(fleet, p, chunk_steps=chunk_steps).row()
    row.update(cell.row_id())
    return row


def _print_row(row: Dict) -> None:
    axis = (f"preset={row['preset']}" if row.get("preset") is not None
            else f"rate={row['rate']}")
    mig = row.get("migration_success_rate")
    print(f"  {axis:>24} {row['algo']:>15s} seed={row['seed']:<5}: "
          f"avail {row.get('availability', 1.0):.4f}  "
          f"mig {('%.2f' % mig) if mig is not None else ' nan'}  "
          f"drop {row['dropped']:>4}  "
          f"p99i {row['p99_lat_inf_s']:7.3f}s  "
          f"done {row['completed_inf']}+{row['completed_trn']}",
          file=sys.stdout)

"""TPU-native geo-distributed GPU-cluster simulator with in-loop RL scheduling.

A brand-new JAX/XLA/pjit framework with the capabilities of
``filrg/distributed_cluster_GPUs``: a continuous-time simulator of a fleet of
GPU datacenters serving inference/training jobs with per-job DVFS
power/latency/energy models, WAN routing, queueing with preemption and elastic
re-allocation, and a family of scheduling/DVFS algorithms up to a constrained
hybrid-action SAC agent (CHSAC-AF) trained online inside the simulation.

Unlike the reference's heapq/PyTorch design, everything here is built
TPU-first: the physics models and arrival generators are jit/vmap-able pure
functions, the event loop is a `lax.scan` whose every step advances exactly to
the next event time over struct-of-arrays state with static shapes, thousands
of rollouts run on-chip via `vmap`, and the RL policy trains with pjit + XLA
collectives over the ICI mesh.
"""

__version__ = "0.1.0"

"""Profiled-inference lookup table: (freq, batch) -> (t1, e1).

Capability parity with `/root/reference/simcore/inference_lut.py:1-22` (note:
dead code there — never imported by the reference simulator; kept in the
inventory for users who profile real inference kernels and want measured
numbers instead of the fitted coefficient models).  Here the table is dense
device arrays with nearest-key lookup, so it jit/vmaps and can be swapped
into the physics path as a drop-in alternative to `step_time_s`/
`task_power_w` for inference jobs.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np


class InferenceLUT(NamedTuple):
    """Dense [n_f, n_b] grids over sorted frequency / batch-size keys."""

    freqs: jnp.ndarray  # [n_f] sorted
    batches: jnp.ndarray  # [n_b] sorted
    t1: jnp.ndarray  # [n_f, n_b] seconds per unit
    e1: jnp.ndarray  # [n_f, n_b] Joules per unit


def build_lut(entries: Dict[Tuple[float, int], Tuple[float, float]]) -> InferenceLUT:
    """{(freq, batch): (t1_s, e1_j)} -> dense LUT (missing cells: nearest row)."""
    freqs = np.array(sorted({f for f, _ in entries}), np.float32)
    batches = np.array(sorted({b for _, b in entries}), np.float32)
    t1 = np.zeros((len(freqs), len(batches)), np.float32)
    e1 = np.zeros_like(t1)
    for (f, b), (t, e) in entries.items():
        t1[np.searchsorted(freqs, f), np.searchsorted(batches, b)] = t
        e1[np.searchsorted(freqs, f), np.searchsorted(batches, b)] = e
    # fill empty cells from the nearest populated one in the same row/col
    for arr in (t1, e1):
        mask = arr == 0
        if mask.any() and (~mask).any():
            fi, bi = np.nonzero(~mask)
            for i, j in zip(*np.nonzero(mask)):
                k = np.argmin((fi - i) ** 2 + (bi - j) ** 2)
                arr[i, j] = arr[fi[k], bi[k]]
    return InferenceLUT(jnp.asarray(freqs), jnp.asarray(batches),
                        jnp.asarray(t1), jnp.asarray(e1))


def time_and_energy(lut: InferenceLUT, freq, batch):
    """Nearest-key lookup (reference `InferenceLUT.time_and_energy` `:13-22`)."""
    fi = jnp.argmin(jnp.abs(lut.freqs - freq))
    bi = jnp.argmin(jnp.abs(lut.batches - batch))
    return lut.t1[fi, bi], lut.e1[fi, bi]

from .physics import (
    PowerCoeffs,
    LatencyCoeffs,
    gpu_power_w,
    task_power_w,
    step_time_s,
    energy_tuple,
)
from .optimizers import (
    best_energy_freq_idx,
    best_nf_grid,
    nf_energy_table,
    min_n_for_sla,
)
from .arrivals import ArrivalParams, lambda_t, next_interarrival, sample_job_size
from .bandit import BanditState, bandit_init, bandit_select, bandit_update

__all__ = [
    "PowerCoeffs",
    "LatencyCoeffs",
    "gpu_power_w",
    "task_power_w",
    "step_time_s",
    "energy_tuple",
    "best_energy_freq_idx",
    "best_nf_grid",
    "nf_energy_table",
    "min_n_for_sla",
    "ArrivalParams",
    "lambda_t",
    "next_interarrival",
    "sample_job_size",
    "BanditState",
    "bandit_init",
    "bandit_select",
    "bandit_update",
]

"""DVFS power / latency / energy models as pure, broadcastable JAX functions.

Capability parity with the reference physics chain
(`/root/reference/simcore/coeffs.py:5-16`, `energy_paper.py:4-12`,
`latency_paper.py:4-9`, `policy_paper.py:32-38`):

    P_gpu(f)  = alpha_p * f^3 + beta_p * f + gamma_p          [W per GPU]
    P_task    = n * P_gpu(f)                                  [W]
    T(n, f)   = alpha_t + beta_t / f               (n == 1)   [s per unit]
              = (alpha_t + beta_t / f + gamma_t*n) / n  (n>1)
    E(n, f)   = P_task * T                                    [J per unit]

The `gamma_t * n` term models the scale-out synchronisation penalty of an
n-GPU job.  All functions broadcast over arbitrary leading axes so the same
code evaluates one (n, f) pair, an (n, f) grid, or a whole job slab under
`vmap` — the MXU/VPU-friendly replacement for the reference's scalar loops.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class PowerCoeffs(NamedTuple):
    """P(f) = alpha_p * f^3 + beta_p * f + gamma_p  (W per GPU).

    Fields are arrays of any (mutually broadcastable) shape; in the fleet
    config they are shaped [n_dc, n_jtype].
    """

    alpha_p: jnp.ndarray
    beta_p: jnp.ndarray
    gamma_p: jnp.ndarray


class LatencyCoeffs(NamedTuple):
    """T(n, f) = alpha_t + beta_t / f + gamma_t * n  (s per unit, see module doc)."""

    alpha_t: jnp.ndarray
    beta_t: jnp.ndarray
    gamma_t: jnp.ndarray


def fmul_pinned(a, b):
    """``a * b`` rounded exactly once, immune to backend FMA contraction.

    XLA CPU's LLVM pipeline may contract ``x + a*b`` into ``fma(a, b, x)``
    depending on the surrounding vectorization context, so the SAME
    expression rounds differently in differently-structured programs —
    measured: it breaks the superstep's bit-identity-with-K=1 goldens
    (`lax.optimization_barrier` does not stop it; the producer is
    duplicated into the consumer kernel and contracted there).  Adding
    ``a * 0.0`` — a runtime zero no compiler may fold (0*inf/NaN and -0
    rules) — forces the product through an fadd; and even if THAT add is
    itself contracted, ``fma(a, b, 0) == fl(a*b)`` bit-exactly.  Every
    compilation therefore rounds the product the same way.

    ``a`` must be finite (``a * 0.0`` must be a true zero); ``b`` and the
    product may be infinite.  ``a`` must also be a RUNTIME value: with a
    compile-time-constant ``a`` XLA folds ``a * 0.0`` to a literal zero
    and elides the fence add entirely (verified in optimized HLO), so a
    constant multiplier belongs in ``b`` — fl(a*b) == fl(b*a) bit-exactly,
    the fence does not.

    The fence zero is pinned to the PRODUCT's dtype: with a weak ``0.0``
    an integer ``a`` (busy counts, GPU counts) promotes the fence to
    weak float64 under jax_enable_x64 — the weak-type-promotion class
    dcg-lint flags — while the strong zero keeps the whole expression in
    the product dtype under both modes, with identical x32 values.
    """
    prod = a * b
    return prod + a * jnp.zeros((), jnp.result_type(prod))


def fdiv_pinned(a, b):
    """``a / b`` computed as an explicitly pinned ``a * (1/b)``.

    A division whose divisor is a COMPILE-TIME CONSTANT (e.g. the DVFS
    ladder in the cap controllers' [J, n_f] grids) MAY get
    strength-reduced to ``a * recip(b)`` in one compiled program and
    stay a true (differently rounded!) division in another — measured: a
    1-ulp `step_time_s` split between the K=1 and unified-superstep
    programs broke the round-7 cap-controller golden, and the rewritten
    multiply additionally FMA-contracts into consuming adds (the
    :func:`fmul_pinned` pathology).  Computing the reciprocal multiply
    EXPLICITLY removes the ambiguity: ``1/b`` is a constant-folded (or
    plain, never approximated) reciprocal and the product is
    contraction-fenced, so every program rounds the result identically.
    The value is fl(a * fl(1/b)) — within 1 ulp of true division, and
    the ONE definition every caller shares.  ``a`` must be finite and
    ``b`` nonzero-finite.
    """
    return fmul_pinned(a, 1.0 / b)


def gpu_power_w(f, pc: PowerCoeffs):
    """Per-GPU power draw at normalised frequency ``f``.

    Every product is contraction-fenced (:func:`fmul_pinned`): cached
    watts must round identically no matter which compiled program
    evaluates the polynomial."""
    f = jnp.maximum(f, 0.0)
    return (fmul_pinned(pc.alpha_p, f**3) + fmul_pinned(pc.beta_p, f)
            + pc.gamma_p)


def task_power_w(n, f, pc: PowerCoeffs):
    """Power of an n-GPU job: n * P_gpu(f); n clamped to >= 0."""
    n = jnp.maximum(n, 0)
    return fmul_pinned(n, gpu_power_w(f, pc))


def step_time_s(n, f, tc: LatencyCoeffs):
    """Seconds per work unit for an n-GPU job at frequency f.

    Matches the reference's piecewise form: for n == 1 the scale-out penalty
    gamma_t*n is NOT applied (single GPU has no sync cost).
    """
    n = jnp.maximum(n, 1)
    f = jnp.maximum(f, 1e-9)
    # fdiv_pinned: with a constant-ladder divisor this division becomes a
    # multiply feeding the add — fence it or the sum rounds differently
    # across compiled programs (cross-program bit-identity, see fmul_pinned)
    base = tc.alpha_t + fdiv_pinned(tc.beta_t, f)
    return jnp.where(n == 1, base, (base + fmul_pinned(tc.gamma_t, n)) / n)


def energy_tuple(n, f, pc: PowerCoeffs, tc: LatencyCoeffs):
    """(T, P, E) per work unit — T in s, P in W, E = P*T in J."""
    T = step_time_s(n, f, tc)
    P = task_power_w(n, f, pc)
    return T, P, T * P


def idle_power_w(n_idle, p_idle, p_sleep, power_gating):
    """Power of idle GPUs: sleep power when power-gated, idle power otherwise."""
    per_gpu = jnp.where(power_gating, p_sleep, p_idle)
    return n_idle * per_gpu


def baseline_dc_power_w(n_busy, n_total, f, p_idle, p_peak, p_sleep, alpha, power_gating):
    """Baseline DC power model (GPUType-level, no per-job coefficients).

    active GPUs: p_idle + p_peak * f^alpha; idle GPUs: sleep (gated) or idle.
    Parity with the reference's `DataCenter.instantaneous_power_w`.
    """
    p_active = n_busy * (p_idle + p_peak * f**alpha)
    return p_active + idle_power_w(n_total - n_busy, p_idle, p_sleep, power_gating)

"""Energy/carbon/cost (n, f) optimizers as vectorized argmins.

Replaces the reference's Python grid searches
(`/root/reference/simcore/policy_paper.py:7-77`) with tensor argmins over a
precomputed [n_max, n_f] energy table — evaluated once per (dc, jtype) at
config time, then reduced on-device.  Tie-breaking matches the reference's
strict `<` scan order (n-major, f-minor, first minimum wins), which matters
for the degenerate objectives (e.g. carbon with CI == 0 scores every
candidate 0.0 and therefore picks n=1, f=freq_levels[0]).
"""

from __future__ import annotations

import jax.numpy as jnp

from .physics import LatencyCoeffs, PowerCoeffs, step_time_s, task_power_w

# Objective codes (static ints so jit specializes the select away).
OBJ_ENERGY = 0
OBJ_CARBON = 1
OBJ_COST = 2


def nf_energy_table(n_max: int, freq_levels, pc: PowerCoeffs, tc: LatencyCoeffs):
    """(T, P, E) tables over the full (n, f) grid.

    Returns three arrays shaped [..., n_max, n_f] where ``...`` broadcasts the
    coefficient shape (e.g. [n_dc, n_jtype]).  Row i corresponds to n = i+1,
    column j to freq_levels[j].
    """
    n = jnp.arange(1, n_max + 1, dtype=jnp.float32)  # [n_max]
    f = jnp.asarray(freq_levels, dtype=jnp.float32)  # [n_f]
    n_b = n[:, None]  # [n_max, 1]
    f_b = f[None, :]  # [1, n_f]
    pc_b = PowerCoeffs(*(c[..., None, None] for c in pc))
    tc_b = LatencyCoeffs(*(c[..., None, None] for c in tc))
    T = step_time_s(n_b, f_b, tc_b)
    P = task_power_w(n_b, f_b, pc_b)
    return T, P, T * P


def best_energy_freq_idx(n, freq_levels, pc: PowerCoeffs, tc: LatencyCoeffs):
    """Index into freq_levels minimising E = P*T at fixed n (first min wins)."""
    f = jnp.asarray(freq_levels, dtype=jnp.float32)
    T = step_time_s(n, f, tc)
    P = task_power_w(n, f, pc)
    return jnp.argmin(T * P)


def best_nf_grid(
    E_table,
    T_table,
    objective: int = OBJ_ENERGY,
    carbon_intensity=0.0,
    price_kwh=0.0,
    deadline_s=None,
):
    """argmin over the (n, f) grid for one (dc, jtype).

    ``E_table``/``T_table`` are the [n_max, n_f] slices from
    :func:`nf_energy_table`.  Returns (n, f_idx) with n in 1..n_max.
    ``objective`` is a static python int (OBJ_*).  Candidates with
    T > deadline_s are excluded; if all are excluded, falls back to
    (1, last f) like the reference.
    """
    if objective == OBJ_CARBON:
        score = E_table * carbon_intensity
    elif objective == OBJ_COST:
        score = (E_table / 3.6e6) * price_kwh
    else:
        score = E_table

    if deadline_s is not None:
        feasible = T_table <= deadline_s
        score = jnp.where(feasible, score, jnp.inf)
        any_feasible = jnp.any(feasible)
    else:
        any_feasible = jnp.bool_(True)

    flat_idx = jnp.argmin(score.reshape(-1))  # first min wins (n-major, f-minor)
    n_f = E_table.shape[-1]
    n_star = flat_idx // n_f + 1
    f_idx = flat_idx % n_f
    # Reference fallback when the deadline filters out everything: n=1, f=max.
    n_star = jnp.where(any_feasible, n_star, 1)
    f_idx = jnp.where(any_feasible, f_idx, n_f - 1)
    return n_star.astype(jnp.int32), f_idx.astype(jnp.int32)


def min_n_for_sla(size, f, tc: LatencyCoeffs, sla_ms, n_max: int):
    """Smallest n in 1..n_max with size * T(n, f) * 1000 <= sla_ms.

    Falls back to n_max when no n meets the SLA (reference
    `simulator_paper_multi.py:1091-1096`).
    """
    n = jnp.arange(1, n_max + 1, dtype=jnp.float32)
    T = step_time_s(n, f, tc)
    ok = size * T * 1000.0 <= sla_ms
    first_ok = jnp.argmax(ok) + 1  # argmax returns first True
    return jnp.where(jnp.any(ok), first_ok, n_max).astype(jnp.int32)

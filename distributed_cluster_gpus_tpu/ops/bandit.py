"""UCB1 DVFS bandit as pure array state.

Capability parity with `/root/reference/simcore/learners.py:5-42`: one arm per
(dc, jtype, freq level); an init-explore phase pulls every arm
``init_explore`` times (in freq-level order), then UCB1
``mean + sqrt(2 ln t / n)`` with reward = -cost_per_unit.  The defaultdict of
Python floats becomes dense [n_dc, n_jtype, n_f] tensors that live on device
and vmap across rollouts.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class BanditState(NamedTuple):
    N: jnp.ndarray  # [n_dc, n_jtype, n_f] pull counts
    S: jnp.ndarray  # [n_dc, n_jtype, n_f] summed rewards
    t: jnp.ndarray  # scalar: total select() calls


def bandit_init(n_dc: int, n_jtype: int, n_f: int) -> BanditState:
    return BanditState(
        N=jnp.zeros((n_dc, n_jtype, n_f), dtype=jnp.int32),
        S=jnp.zeros((n_dc, n_jtype, n_f), dtype=jnp.float32),
        t=jnp.zeros((), dtype=jnp.int32),
    )


def bandit_select(state: BanditState, dc, jtype, init_explore: int = 1):
    """Pick a freq index for (dc, jtype); returns (new_state, f_idx).

    Mirrors the reference ordering: first under-explored arm in freq order
    wins; otherwise the arm with max UCB (ties -> lowest index).
    """
    t = state.t + 1
    N = state.N[dc, jtype]  # [n_f]
    S = state.S[dc, jtype]
    under = N < init_explore
    first_under = jnp.argmax(under)  # first True

    n_safe = jnp.maximum(N, 1)
    mean = jnp.where(N > 0, S / n_safe, 0.0)
    ucb = mean + jnp.sqrt(2.0 * jnp.log(jnp.maximum(t.astype(jnp.float32), 1.0)) / n_safe)
    best_ucb = jnp.argmax(ucb)

    f_idx = jnp.where(jnp.any(under), first_under, best_ucb).astype(jnp.int32)
    return state._replace(t=t), f_idx


def bandit_update(state: BanditState, dc, jtype, f_idx, cost_per_unit) -> BanditState:
    """Record reward = -cost_per_unit for arm (dc, jtype, f_idx).

    Masked write instead of a scatter: under vmap a batched 3-D scatter
    serializes on TPU, a broadcast select does not.
    """
    n_dc, n_jt, n_f = state.N.shape
    m = ((jnp.arange(n_dc) == dc)[:, None, None]
         & (jnp.arange(n_jt) == jtype)[None, :, None]
         & (jnp.arange(n_f) == f_idx)[None, None, :])
    return state._replace(
        N=jnp.where(m, state.N + 1, state.N),
        S=jnp.where(m, state.S - cost_per_unit, state.S),
    )

"""Stochastic workload generators as pure functions of (key, t).

Capability parity with `/root/reference/simcore/arrivals.py`:

* inter-arrival sampling for homogeneous Poisson, sinusoid-modulated Poisson
  (via Ogata thinning against lambda_max = rate * (1 + |amp|)), and 'off';
* job sizes: inference ~ Pareto(x_m=1, alpha=1.8), training ~
  LogNormal(mu=ln 50000, sigma=0.4) clamped to >= 0.1 units.

Everything is shaped for `vmap`: a whole [n_ingress, n_jtype] clock matrix
is refreshed with one call.  Since round 10 these samplers are consumed
ONLY by the workload compiler (`workload.compiler`), which pregenerates
every draw ahead of the event scan — the thinning rejection loop (a
bounded `lax.while_loop`) therefore never executes inside the scanned
step body, where vmap would make every lane pay its max trip count every
step; it runs once per chunk in the pregen prologue (init priming and
the |amp| > 1 / legacy-replay backends).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

MODE_OFF = 0
MODE_POISSON = 1
MODE_SINUSOID = 2


class ArrivalParams(NamedTuple):
    """Per-stream arrival process parameters (broadcastable arrays).

    mode: int code (MODE_*); rate: mean arrivals/s; amp/period: sinusoid shape.
    """

    mode: jnp.ndarray
    rate: jnp.ndarray
    amp: jnp.ndarray
    period: jnp.ndarray


def lambda_t(params: ArrivalParams, t):
    """Instantaneous rate lambda(t) >= 0 for each stream."""
    sin_rate = params.rate * (
        1.0 + params.amp * jnp.sin(2.0 * jnp.pi * (t % params.period) / params.period)
    )
    lam = jnp.where(
        params.mode == MODE_POISSON,
        params.rate,
        jnp.where(params.mode == MODE_SINUSOID, jnp.maximum(0.0, sin_rate), 0.0),
    )
    return lam


def _exponential_safe(key, lam):
    """Exp(lam) sample; +inf when lam <= 0 (mirrors expovariate_safe)."""
    u = jax.random.exponential(key, shape=jnp.shape(lam))
    return jnp.where(lam > 0, u / jnp.maximum(lam, 1e-30), jnp.inf)


def next_interarrival(key, params: ArrivalParams, t):
    """Draw the next inter-arrival gap for one stream at absolute time ``t``.

    Scalar params -> scalar result; use vmap for a clock matrix.  For
    sinusoid streams this runs acceptance-rejection thinning against
    lambda_max = rate * (1 + |amp|), looping until a candidate is accepted —
    also correct for amp > 1 where lambda(t) has hard-zero windows the
    process must skip over (candidates inside a silent window are always
    rejected).  Non-sinusoid lanes start accepted so a vmapped clock matrix
    with mixed modes doesn't pay for the loop.
    """
    lam_max = params.rate * (1.0 + jnp.abs(params.amp))

    def poisson_gap(k):
        return _exponential_safe(k, params.rate)

    def sinusoid_gap(k):
        # skip the loop entirely for non-sinusoid lanes and for rate <= 0
        # (lam_max == 0 would otherwise reject forever: gap = inf and
        # lambda_t(t + inf) is NaN)
        is_sin = (params.mode == MODE_SINUSOID) & (lam_max > 0)

        # Bounded loop, sized for the worst legitimate case: crossing a
        # hard-zero window (amp > 1) of length Z rejects ~Z*lam_max draws
        # in a row (e.g. a 1/3-day trough at rate 10/s with amp 2 needs
        # ~860k candidates), so the bound must be far above that — it
        # exists only to guarantee termination if a corrupted (NaN) clock
        # reaches this loop, where every candidate rejects forever.  If the
        # bound is ever exhausted with a finite clock, accept the last
        # candidate rather than silently killing the stream with inf; a
        # non-finite clock does return inf (the simulation is already
        # poisoned and its `done` latch will end the rollout).
        def cond(carry):
            _, _, accepted, i = carry
            return (~accepted) & (i < (1 << 22))

        def body(carry):
            k, w, _, i = carry
            k, k_w, k_u = jax.random.split(k, 3)
            gap = _exponential_safe(k_w, lam_max)
            w_new = w + gap
            u = jax.random.uniform(k_u)
            lam_cand = lambda_t(params, t + w_new)
            accepted = u <= lam_cand / jnp.maximum(lam_max, 1e-30)
            return k, w_new, accepted, i + 1

        _, w, _, _ = jax.lax.while_loop(
            cond, body, (k, 0.0, ~is_sin, jnp.int32(0)))
        return jnp.where((lam_max > 0) & jnp.isfinite(w), w, jnp.inf)

    gap_poisson = poisson_gap(key)
    gap_sin = sinusoid_gap(key)
    return jnp.where(
        params.mode == MODE_POISSON,
        gap_poisson,
        jnp.where(params.mode == MODE_SINUSOID, gap_sin, jnp.inf),
    )


def sinusoid_gap_from_cum(params: ArrivalParams, t0, s):
    """Inversion sampling of the sinusoid NHPP: the delta >= 0 solving
    ``integral of lambda(u) over (t0, t0 + delta] == s``, for |amp| <= 1
    (where lambda never clips at zero and the integral has a closed form).

    With S_i a running sum of Exp(1) draws, ``t0 + delta(S_i)`` are exactly
    the next arrivals of the process after t0 — the classic time-change
    construction.  Unlike Ogata thinning (`next_interarrival`), every entry
    of ``s`` inverts independently, so a whole arrival table vectorizes with
    no sequential scan and no rejection while_loop — this is the engine's
    parallel arrival pre-generation path (TPU: the thinning loop's data-
    dependent trip counts serialize under vmap; 30 branch-free bisection
    iterations on a monotone bracket do not).

    The integral is computed in gap-relative form (phase of ``t0`` + delta)
    so precision does not decay as the absolute clock grows.  Vectorized
    over ``s``; scalar params.
    """
    r = params.rate
    a_signed = params.amp
    a = jnp.abs(a_signed)
    period = params.period
    w = 2.0 * jnp.pi / period
    phase0 = w * (t0 % period)
    cos0 = jnp.cos(phase0)

    def gap_integral(d):
        return r * d + (r * a_signed / w) * (cos0 - jnp.cos(phase0 + w * d))

    # lambda ranges over [r(1-a), r(1+a)]; the period bound caps the bracket
    # when a -> 1 (the integral gains exactly r*period per full period)
    lo0 = s / jnp.maximum(r * (1.0 + a), 1e-30)
    hi0 = jnp.minimum(s / jnp.maximum(r * (1.0 - a), 1e-9),
                      (s / jnp.maximum(r * period, 1e-30) + 1.0) * period)

    def body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        under = gap_integral(mid) < s
        return jnp.where(under, mid, lo), jnp.where(under, hi, mid)

    lo, hi = jax.lax.fori_loop(0, 30, body, (lo0, hi0))
    return 0.5 * (lo + hi)


def stream_draw_keys(arr_key, stream, count):
    """(k_size, k_gap) for arrival ``count`` of workload stream ``stream``.

    THE single definition of the per-arrival key chain: the engine's
    in-step draw path and both pre-generation table builders must consume
    exactly this sequence or their bit-identity guarantees break.
    """
    k = jax.random.fold_in(jax.random.fold_in(arr_key, stream), count)
    ks = jax.random.split(k)
    return ks[0], ks[1]


JTYPE_INFERENCE = 0
JTYPE_TRAINING = 1


def sample_job_size(key, jtype):
    """Job size in abstract work units.

    inference: Pareto(x_m=1, alpha=1.8) via inverse CDF on u ~ U(0,1];
    training: max(0.1, LogNormal(ln 50000, 0.4)).
    """
    k_u, k_n = jax.random.split(key)
    u = jnp.maximum(1e-9, 1.0 - jax.random.uniform(k_u))
    pareto = 1.0 / u ** (1.0 / 1.8)
    z = jax.random.normal(k_n)
    # strong f32 log operand: a weak Python float computes the log in
    # f64 under jax_enable_x64, so the SAME seed realizes different job
    # sizes in x64 and x32 runs (weak-type-promotion, dcg-lint)
    lognorm = jnp.maximum(0.1, jnp.exp(jnp.log(jnp.float32(50000.0))
                                       + 0.4 * z))
    return jnp.where(jtype == JTYPE_INFERENCE, pareto, lognorm)

"""workload/: trace-compiled arrival streams + time-varying energy signals.

The workload layer turns declarative scenario specs (`spec.WorkloadSpec`
— synthetic Poisson/sinusoid, replayed traces, piecewise rate timelines,
diurnal/flash-crowd presets, price/carbon signal timelines) into the
fixed-shape pregenerated per-chunk event tables the scanned engine
consumes by cursor (`compiler.WorkloadProgram`), and into compiled
signal samplers the eco optimizers / routers / RL observations read
(`signals.CompiledSignals`).  See docs/workloads.md.
"""

from .compiler import WorkloadProgram, compile_workload, legacy_spec
from .presets import PRESETS, make_preset
from .signals import CompiledSignals, compile_signals, legacy_signals
from .spec import (
    STREAM_KINDS,
    SignalSpec,
    StreamSpec,
    WorkloadSpec,
    load_workload_json,
    workload_from_dict,
)

__all__ = [
    "WorkloadProgram", "compile_workload", "legacy_spec",
    "PRESETS", "make_preset",
    "CompiledSignals", "compile_signals", "legacy_signals",
    "STREAM_KINDS", "SignalSpec", "StreamSpec", "WorkloadSpec",
    "load_workload_json", "workload_from_dict",
]

"""Canonical workload scenarios: diurnal fleets, flash crowds, surges.

Every preset is a plain :class:`~.spec.WorkloadSpec` builder — the same
object a JSON spec file loads into — so the CLI (`run_sim.py
--workload NAME`), bench.py's trace-replay probe, and the tests all
pull scenarios from one registry (:data:`PRESETS`).

Rates here are per-STREAM (per ingress, per jtype); the paper fleet has
8 ingresses, so aggregate arrivals are ~8x the inference figure.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .spec import SignalSpec, StreamSpec, WorkloadSpec

DAY_S = 86400.0
WEEK_S = 7 * DAY_S


def diurnal_rates(base: float, peak_ratio: float = 3.0, n_bins: int = 24,
                  phase_h: float = 0.0) -> np.ndarray:
    """[n_bins] arrivals/s: a smooth day curve peaking at ``peak_ratio`` x
    the trough, mean ~= base, shifted by ``phase_h`` hours (regional
    offsets)."""
    h = (np.arange(n_bins) + 0.5) * (24.0 / n_bins) + phase_h
    shape = 1.0 + (peak_ratio - 1.0) / (peak_ratio + 1.0) * np.sin(
        2.0 * np.pi * (h - 10.0) / 24.0)
    return np.maximum(0.0, base * shape / shape.mean())


def add_flash_crowd(rates: np.ndarray, bin_s: float, t0_s: float,
                    dur_s: float, mult: float) -> np.ndarray:
    """Overlay one flash-crowd window (``mult`` x rate) on a timeline."""
    out = np.asarray(rates, np.float64).copy()
    b0 = int(t0_s // bin_s)
    b1 = max(b0 + 1, int(np.ceil((t0_s + dur_s) / bin_s)))
    out[b0:b1] *= mult
    return out


def _weekly_price(fleet) -> np.ndarray:
    """[168] USD/kWh: the paper's daily tariff tiled over a week with a
    weekend off-peak discount — a genuinely time-varying price the
    static hourly table cannot express."""
    day = np.asarray(fleet.price_hourly, np.float64)
    week = np.tile(day, 7)
    week[5 * 24:] *= 0.8  # weekend discount
    return week


def _diurnal_carbon(fleet, n_bins: int = 24) -> np.ndarray:
    """[n_bins, n_dc] gCO2/kWh: per-DC carbon swinging around the static
    map (solar dip mid-day, fossil peak in the evening).  DCs without
    carbon data stay at 0 (the preserved reference quirk)."""
    base = np.asarray(fleet.carbon, np.float64)
    h = (np.arange(n_bins) + 0.5) * (24.0 / n_bins)
    swing = 1.0 + 0.35 * np.sin(2.0 * np.pi * (h - 4.0) / 24.0)
    return np.maximum(0.0, base[None, :] * swing[:, None])


def flash_crowd(fleet, *, base_rate: float = 4.0, spike_mult: float = 10.0,
                horizon_s: float = 7200.0, bin_s: float = 300.0,
                observe: bool = False) -> WorkloadSpec:
    """Bench probe scenario: steady inference + one 10x flash crowd
    mid-horizon, light Poisson training, legacy-equivalent signals."""
    n_bins = int(np.ceil(horizon_s / bin_s))
    rates = np.full(n_bins, base_rate, np.float64)
    rates = add_flash_crowd(rates, bin_s, 0.4 * horizon_s,
                            0.1 * horizon_s, spike_mult)
    return WorkloadSpec(
        streams=(
            StreamSpec(kind="rate_timeline", rates=rates, bin_s=bin_s),
            StreamSpec(kind="poisson", rate=0.05),
        ),
        signals=SignalSpec(price=None, carbon=_diurnal_carbon(fleet),
                           bin_s=3600.0, periodic=True, observe=observe),
        name="flash_crowd")


def diurnal_flash_week(fleet, *, base_rate: float = 0.15,
                       trn_rate: float = 0.01,
                       observe: bool = True) -> WorkloadSpec:
    """The week-horizon capacity-planning scenario (ROADMAP item 5 /
    acceptance run): per-region diurnal inference peaks staggered by
    each ingress's longitude band, two flash crowds (a Monday spike and
    a weekend event), training surges correlated with (lagging) the
    inference bursts, weekly price tariff and diurnal per-DC carbon —
    all observable by the routers and RL policy."""
    bin_s = 3600.0
    n_bins = int(WEEK_S // bin_s)
    # rough longitude-band phase per paper-world ingress order:
    # US, US, EU, EU, APAC, APAC, SA, ME (see configs.paper)
    phases = {"US": -8.0, "EU": 0.0, "APAC": 8.0, "SA": -5.0, "ME": 3.0}
    regions = ["US", "US", "EU", "EU", "APAC", "APAC", "SA", "ME"]
    pairs = []
    for i in range(fleet.n_ing):
        region = regions[i % len(regions)]
        day = diurnal_rates(base_rate, peak_ratio=4.0, n_bins=24,
                            phase_h=phases[region])
        inf_rates = np.tile(day, n_bins // 24 + 1)[:n_bins]
        # flash crowds: Monday 18:00 spike everywhere, Saturday event in
        # the US/EU lanes only
        inf_rates = add_flash_crowd(inf_rates, bin_s, 0 * DAY_S + 18 * 3600,
                                    2 * 3600, 6.0)
        if region in ("US", "EU"):
            inf_rates = add_flash_crowd(inf_rates, bin_s,
                                        5 * DAY_S + 12 * 3600, 3 * 3600, 4.0)
        # correlated training surge: retrain waves lag the inference
        # bursts by ~6 h at a scaled-down rate
        trn_rates = np.full(n_bins, trn_rate, np.float64)
        trn_rates += 0.08 * np.roll(inf_rates - inf_rates.mean(), 6).clip(0)
        pairs.append((
            StreamSpec(kind="rate_timeline", rates=inf_rates, bin_s=bin_s),
            StreamSpec(kind="rate_timeline", rates=trn_rates.clip(0),
                       bin_s=bin_s),
        ))
    return WorkloadSpec(
        streams=tuple(pairs),
        signals=SignalSpec(price=_weekly_price(fleet),
                           carbon=_diurnal_carbon(fleet),
                           bin_s=bin_s, periodic=True, observe=observe),
        name="diurnal_flash_week")


def legacy_signals_only(fleet, *, observe: bool = False,
                        params=None) -> WorkloadSpec:
    """The legacy synthetic arrival fields with the legacy price/carbon
    tables lifted into explicit timelines — for A/B-ing the signal path
    (time-varying columns/accruals on the exact legacy workload)."""
    from .compiler import legacy_spec

    if params is None:
        from ..models.structs import SimParams

        params = SimParams()
    base = legacy_spec(params)
    return WorkloadSpec(
        streams=base.streams,
        signals=SignalSpec(price=np.asarray(fleet.price_hourly, np.float64),
                           carbon=np.asarray(fleet.carbon, np.float64)[None, :],
                           bin_s=3600.0, periodic=True, observe=observe),
        name="legacy_signals")


PRESETS = {
    "flash_crowd": flash_crowd,
    "diurnal_flash_week": diurnal_flash_week,
    "legacy_signals": legacy_signals_only,
}


def make_preset(name: str, fleet, **kw) -> WorkloadSpec:
    if name not in PRESETS:
        raise ValueError(
            f"unknown workload preset {name!r}; choices: "
            f"{', '.join(sorted(PRESETS))}")
    return PRESETS[name](fleet, **kw)

"""The workload compiler: scenario specs -> per-chunk pregenerated tables.

`WorkloadProgram` owns every arrival draw of a run.  The engine calls
:meth:`WorkloadProgram.tables` once per chunk (inside the jitted
`_run_chunk`, BEFORE the event scan) to pregenerate a fixed-shape table
of the next ``n_steps`` arrivals per stream — job sizes and
next-arrival clocks — which the scanned step consumes by cursor
(`arr_count`): two gathers replace the per-step fold/split/sample
chains, so NO workload draw (and in particular no thinning
``while_loop``) ever executes inside the step body, for any stream
kind (pinned by `scripts/count_step_ops.py` + test_perf_structure).

Chunk-invariance (the round-10 contract that retired the re-anchoring
caveat): every generated value is a pure function of (seed, stream,
draw index) plus per-stream carries that compose EXACTLY across chunk
boundaries:

* per-draw keys come from `ops.arrivals.stream_draw_keys` — the single
  key-fold chain shared with every earlier round (legacy goldens hold);
* clock recursions are LEFT FOLDS (`t' = t + gap`, `S' = S + e`)
  computed by a 1-add-per-step prefix scan, so splitting a run into
  chunks reproduces the unsplit fold bit-for-bit (a parallel
  ``cumsum``'s log-depth association would not — measured on CPU);
  the fold carries live in SimState (``next_arrival`` / ``arr_cum``);
* the sinusoid inversion anchors at the stream's fixed first-arrival
  epoch (``arr_epoch``) instead of the chunk-entry clock, so the
  expensive bisection stays FULLY PARALLEL over the table while the
  anchor never moves.

Consequently a run chunked any way — and at any superstep K — realizes
byte-identical results (tests/test_workload.py pins it), and the
"chunk-boundary pregen re-anchoring" ulp caveat that trailed rounds
6-9 is retired.

Stream kind -> generator family:

* ``poisson``      — gap fold `t' = t + Exp(k)/rate`; bit-exact with the
  legacy in-step draw path (pregen on/off now realize the SAME bytes).
* ``sinusoid``, |amp| <= 1, inversion on (default) — epoch-anchored
  time-change inversion of the closed-form integrated rate (parallel
  bisection per entry, `ops.arrivals.sinusoid_gap_from_cum`).
* ``sinusoid``, |amp| > 1 or ``DCG_ARRIVAL_PREGEN=0`` — sequential
  thinning replay (`ops.arrivals.next_interarrival` per entry): the
  exact legacy realization, now generated ahead of the scan.
* ``trace``        — cursor gathers into the replayed (times, sizes).
* ``rate_timeline``— `S' = S + Exp(k)` fold + parallel piecewise-linear
  inversion of the integrated rate (searchsorted, no loop at all).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.arrivals import (
    MODE_OFF,
    MODE_POISSON,
    MODE_SINUSOID,
    ArrivalParams,
    next_interarrival,
    sample_job_size,
    sinusoid_gap_from_cum,
    stream_draw_keys,
)
from .signals import CompiledSignals, compile_signals
from .spec import StreamSpec, WorkloadSpec


def legacy_spec(params) -> WorkloadSpec:
    """The synthetic workload a plain SimParams describes, as a spec.

    This is how every pre-workload-layer config flows through the
    compiler: the (inf_mode, inf_rate, inf_amp, inf_period) /
    (trn_mode, trn_rate) fields become two broadcast StreamSpecs with
    the exact legacy constants (training period 3600, amp 0 — mirroring
    the retired `engine._arrival_params`).  No signals: the static
    hourly price / per-DC carbon tables stay in charge, so the compiled
    program is bit-identical to the pre-workload engine.
    """
    return WorkloadSpec(
        streams=(
            StreamSpec(kind=params.inf_mode, rate=params.inf_rate,
                       amp=params.inf_amp, period=params.inf_period),
            StreamSpec(kind=params.trn_mode, rate=params.trn_rate,
                       amp=0.0, period=3600.0),
        ),
        signals=None, name="legacy_params")


def compile_workload(fleet, params) -> "WorkloadProgram":
    """(fleet, SimParams) -> the run's WorkloadProgram.

    ``params.workload`` None routes the legacy synthetic fields through
    the same compiler (`legacy_spec`)."""
    spec = params.workload if params.workload is not None else legacy_spec(params)
    return WorkloadProgram(fleet, params, spec)


class WorkloadProgram:
    """Compiled workload for one (fleet, params, spec) specialization."""

    def __init__(self, fleet, params, spec: WorkloadSpec):
        self.fleet = fleet
        self.params = params
        self.spec = spec
        self.streams = spec.resolve(fleet.n_ing)
        # flat stream order is ing * 2 + jt — the engine's clock-matrix
        # layout and the key-fold chain's stream id
        self.flat = tuple(self.streams[i][j]
                          for i in range(fleet.n_ing) for j in (0, 1))
        self.n_streams = len(self.flat)
        self.signals: Optional[CompiledSignals] = compile_signals(
            spec.signals, fleet)
        # device constants for trace / rate_timeline streams
        self._trace = {}
        self._tl = {}
        for s, st in enumerate(self.flat):
            if st.kind == "trace":
                times = np.asarray(st.times, np.float64).reshape(-1)
                if times.size and np.any(np.diff(times) < 0):
                    raise ValueError(
                        f"trace stream {s}: times must be non-decreasing")
                sizes = (None if st.sizes is None
                         else np.asarray(st.sizes, np.float32).reshape(-1))
                if sizes is not None and sizes.shape != times.shape:
                    raise ValueError(
                        f"trace stream {s}: {sizes.shape[0]} sizes for "
                        f"{times.shape[0]} times")
                self._trace[s] = (jnp.asarray(times),
                                  None if sizes is None
                                  else jnp.asarray(sizes))
            elif st.kind == "rate_timeline":
                rates = np.asarray(st.rates, np.float64).reshape(-1)
                if rates.size == 0 or np.any(~np.isfinite(rates)) \
                        or np.any(rates < 0):
                    raise ValueError(
                        f"rate_timeline stream {s}: rates must be finite "
                        "and >= 0")
                if st.periodic and rates.sum() <= 0:
                    raise ValueError(
                        f"rate_timeline stream {s}: periodic timeline "
                        "needs a positive total rate")
                qc = np.concatenate(
                    [[0.0], np.cumsum(rates * st.bin_s)])
                self._tl[s] = (jnp.asarray(qc), jnp.asarray(rates),
                               float(st.bin_s), bool(st.periodic))

    # ------------------------------------------------------------------
    # static per-stream facts
    # ------------------------------------------------------------------

    def _family(self, st: StreamSpec, inversion: bool) -> str:
        if st.kind == "sinusoid":
            if abs(st.amp) > 1.0 or not inversion:
                return "thinning"
            return "sin_inv"
        return st.kind  # off | poisson | trace | rate_timeline

    def uses_cum(self, inversion: bool = True) -> np.ndarray:
        """[S] bool: streams whose fold carry is the cumulative Exp sum
        (``SimState.arr_cum``) rather than the arrival clock itself."""
        return np.asarray([
            self._family(st, inversion) in ("sin_inv", "rate_timeline")
            for st in self.flat])

    def mean_rate(self) -> float:
        return self.spec.mean_rate(self.fleet.n_ing)

    def _arr_p(self, st: StreamSpec) -> ArrivalParams:
        mode = {"off": MODE_OFF, "poisson": MODE_POISSON,
                "sinusoid": MODE_SINUSOID}[st.kind]
        return ArrivalParams(
            mode=jnp.int32(mode), rate=jnp.float32(st.rate),
            amp=jnp.float32(st.amp), period=jnp.float32(st.period))

    # ------------------------------------------------------------------
    # initial clocks (draw #0 of every stream's dedicated chain)
    # ------------------------------------------------------------------

    def init_clocks(self, arr_key, tdtype):
        """{"next_arrival", "arr_cum", "arr_epoch"} — [n_ing, 2] arrays.

        Draw #0 uses the UNSPLIT fold key (`fold_in(fold_in(key, s), 0)`)
        exactly as every earlier round's `init_state` did, so legacy
        synthetic workloads prime bit-identical clocks."""
        t0s, cums = [], []
        for s, st in enumerate(self.flat):
            k0 = jax.random.fold_in(jax.random.fold_in(arr_key, s), 0)
            if st.kind in ("off", "poisson", "sinusoid"):
                gap = next_interarrival(k0, self._arr_p(st), st.phase_s)
                t0, cum = gap, jnp.zeros((), tdtype)
            elif st.kind == "trace":
                times, _ = self._trace[s]
                t0 = (times[0].astype(tdtype) if times.shape[0]
                      else jnp.asarray(jnp.inf, tdtype))
                cum = jnp.zeros((), tdtype)
            else:  # rate_timeline
                e0 = jax.random.exponential(k0).astype(tdtype)
                t0 = self._invert_timeline(s, e0[None])[0]
                cum = e0
            t0s.append(jnp.asarray(t0, tdtype))
            cums.append(jnp.asarray(cum, tdtype))
        shape = (self.fleet.n_ing, 2)
        t0 = jnp.stack(t0s).reshape(shape)
        return {"next_arrival": t0,
                "arr_cum": jnp.stack(cums).reshape(shape),
                # a distinct buffer: epoch and clock start equal but are
                # separate donated leaves of the scanned SimState
                "arr_epoch": jnp.copy(t0)}

    # ------------------------------------------------------------------
    # per-chunk tables
    # ------------------------------------------------------------------

    def tables(self, state, n_steps: int, inversion: bool = True,
               trace=None):
        """Pregenerate the next ``n_steps`` arrivals of every stream.

        Returns {"sizes": [S, n] f32, "tnext": [S, n] tdtype,
        "cum": [S, n] tdtype, "c0": [S] i32}; the engine consumes
        ``sizes``/``tnext`` by cursor inside the scan and
        `advance_carries` commits ``cum`` after it.

        ``trace`` optionally overrides the baked trace constants with
        RUNTIME arrays: ``{s: (times [cap] f64, sizes [cap] f32 | None,
        n_valid i32)}``.  The capacity is static (it keys the trace) but
        ``n_valid`` is a dynamic scalar, so an append-only trace grows
        WITHOUT retracing as long as it fits the padded capacity —
        entries at index >= n_valid read as +inf (stream quiet), exactly
        what the baked path realizes past a trace's end.  This is the
        twin's incremental-ingest hook (twin/ingest.py); batch runs
        never pass it."""
        S, n = self.n_streams, n_steps
        if trace:
            for s in trace:
                if self.flat[s].kind != "trace":
                    raise ValueError(
                        f"trace override for stream {s} "
                        f"(kind {self.flat[s].kind!r}, not 'trace')")
        td = state.t.dtype
        c0 = state.arr_count.reshape(S)
        t0 = state.next_arrival.reshape(S)
        cum0 = state.arr_cum.reshape(S)
        epoch = state.arr_epoch.reshape(S)
        arr_key = state.arr_key

        counts = c0[:, None] + jnp.arange(n, dtype=jnp.int32)[None, :]
        sizes_rows, inc_rows, init_row = [], [], []
        post = []  # (s, fn(fold_row) -> tnext_row) applied after the fold
        thin = []  # thinning streams: (s, StreamSpec)
        for s, st in enumerate(self.flat):
            fam = self._family(st, inversion)
            jt = s % 2
            tr_o = None if trace is None else trace.get(s)
            # draw keys/sizes only for streams that CONSUME them: `off`
            # lanes (every unnamed ingress of a list-form spec) and
            # traces with explicit sizes would otherwise pay n_steps
            # fold/split/sample chains per chunk for discarded values
            if tr_o is not None:
                explicit_sizes = (tr_o[1] is not None
                                  and tr_o[0].shape[0] > 0)
            else:
                explicit_sizes = (st.kind == "trace"
                                  and self._trace[s][1] is not None
                                  and self._trace[s][0].shape[0] > 0)
            need_size_keys = fam != "off" and not explicit_sizes
            need_gap_keys = fam in ("poisson", "sin_inv", "rate_timeline")
            if need_size_keys or need_gap_keys:
                k_size, k_gap = jax.vmap(
                    lambda c, s=s: stream_draw_keys(arr_key, s, c))(counts[s])
            if explicit_sizes:
                if tr_o is not None:
                    cap = tr_o[0].shape[0]
                    sizes = tr_o[1][jnp.clip(counts[s] - 1, 0, cap - 1)]
                else:
                    times, tr_sizes = self._trace[s]
                    N = times.shape[0]
                    sizes = tr_sizes[jnp.clip(counts[s] - 1, 0, N - 1)]
            elif need_size_keys:
                sizes = jax.vmap(
                    lambda k, jt=jt: sample_job_size(k, jt))(k_size)
            else:  # off (or an empty trace): the stream never fires
                sizes = jnp.zeros((n,), jnp.float32)
            sizes_rows.append(sizes.astype(jnp.float32))

            if fam == "poisson":
                lam = jnp.float32(st.rate)
                u = jax.vmap(jax.random.exponential)(k_gap)
                gaps = jnp.where(lam > 0, u / jnp.maximum(lam, 1e-30),
                                 jnp.inf)
                inc_rows.append(gaps.astype(td))
                init_row.append(t0[s])
                post.append((s, lambda fold_row: fold_row))
            elif fam in ("sin_inv", "rate_timeline"):
                e = jax.vmap(jax.random.exponential)(k_gap).astype(td)
                inc_rows.append(e)
                init_row.append(cum0[s])
                if fam == "sin_inv":
                    arr_p = self._arr_p(st)
                    anchor = epoch[s] + jnp.asarray(st.phase_s, td)

                    def sin_post(fold_row, arr_p=arr_p, anchor=anchor,
                                 ep=epoch[s], st=st):
                        delta = sinusoid_gap_from_cum(arr_p, anchor,
                                                      fold_row)
                        delta = jnp.where(jnp.float32(st.rate) > 0, delta,
                                          jnp.inf)
                        return (ep + delta).astype(td)

                    post.append((s, sin_post))
                else:
                    post.append((s, lambda fold_row, s=s:
                                 self._invert_timeline(s, fold_row)))
            elif fam == "thinning":
                inc_rows.append(jnp.zeros((n,), td))
                init_row.append(t0[s])
                thin.append(s)
                post.append((s, None))  # filled by the thinning replay
            elif fam == "trace":
                idx = counts[s]
                if tr_o is not None:
                    # runtime trace: the gather bound is the DYNAMIC
                    # n_valid, so appended entries (written into the
                    # padded capacity) become visible without retracing
                    times_o, _sz, n_valid = tr_o
                    cap = times_o.shape[0]
                    if cap:
                        tn = jnp.where(
                            idx < n_valid,
                            times_o[jnp.clip(idx, 0, cap - 1)].astype(td),
                            jnp.asarray(jnp.inf, td))
                    else:
                        tn = jnp.full((n,), jnp.inf, td)
                else:
                    times, _ = self._trace[s]
                    N = times.shape[0]
                    if N:
                        tn = jnp.where(
                            idx < N,
                            times[jnp.clip(idx, 0, N - 1)].astype(td),
                            jnp.asarray(jnp.inf, td))
                    else:
                        tn = jnp.full((n,), jnp.inf, td)
                inc_rows.append(jnp.zeros((n,), td))
                init_row.append(t0[s])
                post.append((s, lambda fold_row, tn=tn: tn))
            else:  # off
                inc_rows.append(jnp.zeros((n,), td))
                init_row.append(t0[s])
                post.append((s, lambda fold_row:
                             jnp.full((n,), jnp.inf, td)))

        # THE prefix fold: one scan, [S]-vector carry, one add per step.
        # A left fold is the whole chunk-invariance story — the carry
        # (arrival clock / cumulative Exp sum) re-enters the next
        # chunk's fold in exactly the association the unsplit fold uses.
        inc = jnp.stack(inc_rows)  # [S, n]
        init = jnp.stack(init_row)  # [S]

        def fold_body(carry, x):
            carry = carry + x
            return carry, carry

        _, fold = jax.lax.scan(fold_body, init, inc.T)
        fold = fold.T  # [S, n]

        tnext_rows = [None] * S
        for s, fn in post:
            if fn is not None:
                tnext_rows[s] = fn(fold[s])
        if thin:
            thin_rows = self._thinning_replay(
                arr_key, [self.flat[s] for s in thin],
                jnp.asarray(thin, jnp.int32), c0[jnp.asarray(thin)],
                t0[jnp.asarray(thin)], n, td)
            for row, s in enumerate(thin):
                tnext_rows[s] = thin_rows[row]
        return {"sizes": jnp.stack(sizes_rows),
                "tnext": jnp.stack(tnext_rows).astype(td),
                "cum": fold,
                "c0": c0}

    def _thinning_replay(self, arr_key, specs, s_idx, c0, t0, n, td):
        """Sequential replay of the legacy thinning recursion for the
        sinusoid streams that need it (|amp| > 1 hard-zero windows, or
        the DCG_ARRIVAL_PREGEN=0 legacy-draw mode): one table entry per
        scan iteration, bit-exact with the historical in-step draws."""
        arr_p = ArrivalParams(
            mode=jnp.full((len(specs),), MODE_SINUSOID, jnp.int32),
            rate=jnp.asarray([st.rate for st in specs], jnp.float32),
            amp=jnp.asarray([st.amp for st in specs], jnp.float32),
            period=jnp.asarray([st.period for st in specs], jnp.float32))
        phase = jnp.asarray([st.phase_s for st in specs], td)

        def per_stream(s, c_start, t_start, p, ph):
            def body(t, i):
                _, k_gap = stream_draw_keys(arr_key, s, c_start + i)
                t_next = t + next_interarrival(k_gap, p, t + ph)
                return t_next, t_next

            _, out = jax.lax.scan(body, t_start,
                                  jnp.arange(n, dtype=jnp.int32))
            return out

        return jax.vmap(per_stream)(s_idx, c0, t0.astype(td), arr_p, phase)

    def _invert_timeline(self, s: int, svals):
        """Lambda^{-1}(s) for a piecewise-constant rate timeline — fully
        parallel over ``svals`` (searchsorted + one divide)."""
        qc, rates, bin_s, periodic = self._tl[s]
        T = rates.shape[0]
        td = svals.dtype
        qc = qc.astype(td)
        rates_td = rates.astype(td)
        if periodic:
            total = qc[-1]
            wraps = jnp.floor(svals / total)
            srem = svals - wraps * total
            base_t = wraps * (T * bin_s)
        else:
            srem = svals
            base_t = jnp.zeros_like(svals)
        b = jnp.clip(jnp.searchsorted(qc, srem, side="right") - 1, 0, T - 1)
        rb = rates_td[b]
        # bin_s pinned to the time dtype: the weak Python float computes
        # `b * bin_s` in float64 under jax_enable_x64, so the SAME spec
        # realizes different arrival times in x64 vs x32 runs
        # (weak-type-promotion, dcg-lint)
        bs = jnp.asarray(bin_s, td)
        t_in = b * bs + (srem - qc[b]) / jnp.maximum(rb, 1e-30)
        # zero-rate landing bins: reachable only at exact boundaries
        # (srem == qc[b]) — the stream is silent there, so the arrival
        # never comes
        t_in = jnp.where(rb > 0, t_in,
                         jnp.where(srem <= qc[b], b * bs, jnp.inf))
        if not periodic:
            # a finite timeline ENDS: cumulative demand beyond its total
            # integrated rate never arrives ("burst then silence" — the
            # spec contract; extrapolating the last bin's rate forever
            # would silently un-bound a bounded scenario)
            t_in = jnp.where(srem > qc[-1], jnp.inf, t_in)
        return (base_t + t_in).astype(td)

    # ------------------------------------------------------------------
    # post-chunk carry commit
    # ------------------------------------------------------------------

    def advance_carries(self, state, pre, inversion: bool = True):
        """Commit the cumulative-sum fold carries the chunk consumed.

        Runs OUTSIDE the scan (one gather per stream in the chunk
        epilogue, zero step-body cost): ``arr_cum`` advances to the fold
        value of the last consumed table entry so the next chunk's fold
        re-enters exactly where the unsplit fold would be.  Streams
        whose carry is the clock itself (``next_arrival`` — poisson /
        thinning) already advanced in-step."""
        mask = self.uses_cum(inversion)
        if not mask.any():
            return state
        S = self.n_streams
        n = pre["cum"].shape[1]
        consumed = state.arr_count.reshape(S) - pre["c0"]
        idx = jnp.clip(consumed - 1, 0, n - 1)
        picked = pre["cum"][jnp.arange(S), idx]
        newc = jnp.where(jnp.asarray(mask) & (consumed > 0), picked,
                         state.arr_cum.reshape(S))
        return state.replace(arr_cum=newc.reshape(state.arr_cum.shape))

"""Declarative workload scenario specs: arrival streams + energy signals.

A :class:`WorkloadSpec` is the static description of everything the world
throws at the fleet: one arrival :class:`StreamSpec` per (ingress, jtype)
workload stream, plus an optional :class:`SignalSpec` describing the
time-varying energy-price and carbon-intensity timelines the eco
optimizers, routers, and RL observations consume.  The spec is pure data
— numpy arrays and floats — and the workload *compiler*
(`workload.compiler.WorkloadProgram`) turns it into the fixed-shape,
per-chunk pregenerated event tables the scanned engine consumes by
cursor (docs/workloads.md).

Stream kinds:

* ``off`` — no arrivals.
* ``poisson`` — homogeneous rate; bit-exact replay of the legacy
  in-step exponential draw chain (`ops.arrivals`), so legacy configs
  routed through the compiler reproduce their goldens byte-for-byte.
* ``sinusoid`` — sinusoid-modulated NHPP (rate, amp, period, phase_s).
  |amp| <= 1 compiles to the parallel time-change inversion; |amp| > 1
  (hard-zero windows) to the sequential thinning replay.
* ``trace`` — replay explicit per-arrival ``times`` (absolute seconds,
  non-decreasing) with optional per-arrival ``sizes`` (work units;
  omitted -> sizes come from the standard keyed distributions, so a
  trace stays size-comparable with synthetic runs).
* ``rate_timeline`` — piecewise-constant rate lambda(t) over fixed-width
  bins (``rates``, ``bin_s``, optionally periodic) — the building block
  for diurnal curves, flash crowds, and correlated surges
  (`workload.presets`).  Arrivals are drawn by time-change inversion of
  the piecewise-linear integrated rate: fully parallel per chunk.

Hashing: specs hold numpy arrays, so like :class:`models.FleetSpec` they
hash/compare by identity — build one per run shape and reuse it (it
rides `SimParams.workload`, which must stay hashable for jit closures).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Tuple

import numpy as np

STREAM_KINDS = ("off", "poisson", "sinusoid", "trace", "rate_timeline")

#: jtype axis order everywhere in the engine: 0 = inference, 1 = training
JTYPE_NAMES = ("inference", "training")


@dataclasses.dataclass(frozen=True)
class StreamSpec:
    """One arrival stream (one (ingress, jtype) lane of the clock matrix)."""

    kind: str = "off"
    # poisson / sinusoid
    rate: float = 0.0  # mean arrivals/s (sinusoid: carrier rate)
    amp: float = 0.0
    period: float = 3600.0
    phase_s: float = 0.0  # sinusoid phase offset (multi-region staggering)
    # trace
    times: Optional[np.ndarray] = None  # [N] absolute s, non-decreasing
    sizes: Optional[np.ndarray] = None  # [N] work units (optional)
    # rate_timeline
    rates: Optional[np.ndarray] = None  # [T] arrivals/s, piecewise constant
    bin_s: float = 3600.0
    periodic: bool = False  # wrap the timeline instead of clamping to 0

    def __post_init__(self):
        if self.kind not in STREAM_KINDS:
            raise ValueError(
                f"unknown stream kind {self.kind!r}; choices: {STREAM_KINDS}")
        if self.kind == "trace" and self.times is None:
            raise ValueError("trace stream needs a `times` array")
        if self.kind == "rate_timeline" and self.rates is None:
            raise ValueError("rate_timeline stream needs a `rates` array")

    def mean_rate(self) -> float:
        """Long-run arrivals/s (queue-ring sizing; 0 for exhausted traces)."""
        if self.kind == "poisson":
            return max(0.0, self.rate)
        if self.kind == "sinusoid":
            # mean of max(0, r(1+a sin)) over a period; for |a|<=1 it is r
            if abs(self.amp) <= 1.0:
                return max(0.0, self.rate)
            ph = np.linspace(0.0, 2 * np.pi, 512, endpoint=False)
            return float(np.maximum(
                0.0, self.rate * (1.0 + self.amp * np.sin(ph))).mean())
        if self.kind == "rate_timeline":
            return float(np.asarray(self.rates, np.float64).mean())
        if self.kind == "trace":
            t = np.asarray(self.times, np.float64)
            if t.size < 2:
                return 0.0
            span = float(t[-1] - t[0])
            return t.size / span if span > 0 else 0.0
        return 0.0


@dataclasses.dataclass(frozen=True)
class SignalSpec:
    """Time-varying energy price + carbon intensity timelines.

    Both are piecewise-constant over ``bin_s``-wide bins starting at
    t=0; ``periodic=True`` wraps (a 24 h tariff repeats daily — the
    legacy `FleetSpec.price_hourly` semantics), else the last bin
    extends.  ``carbon`` is [T, n_dc] (or [n_dc] for a constant map,
    the legacy `FleetSpec.carbon` semantics).  ``observe=True`` appends
    the sampled price + per-DC carbon to the RL observation vector
    (grows `SimParams.obs_dim` by 1 + n_dc).
    """

    price: Optional[np.ndarray] = None  # [T] USD/kWh
    carbon: Optional[np.ndarray] = None  # [T, n_dc] or [n_dc] gCO2/kWh
    bin_s: float = 3600.0
    periodic: bool = True
    observe: bool = False

    def __post_init__(self):
        if self.price is None and self.carbon is None:
            raise ValueError("SignalSpec needs a price and/or carbon array")


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """The full scenario: arrival streams per (ingress, jtype) + signals.

    ``streams`` is either a 2-tuple ``(inference, training)`` broadcast
    over every ingress (the legacy shape), or an [n_ing]-tuple of such
    pairs (multi-region scenarios — per-ingress diurnal phases, regional
    flash crowds).  `resolve(n_ing)` normalizes to the full matrix.
    """

    streams: Tuple  # (inf, trn) | ((inf, trn), ... per ingress)
    signals: Optional[SignalSpec] = None
    name: str = "custom"

    # identity hash/eq (FleetSpec convention): specs carry numpy arrays
    # and ride hashable SimParams — build once, reuse.
    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other

    def resolve(self, n_ing: int) -> Tuple[Tuple[StreamSpec, StreamSpec], ...]:
        """Per-(ingress, jtype) stream matrix as an [n_ing] tuple of pairs."""
        s = self.streams
        if len(s) == 2 and isinstance(s[0], StreamSpec):
            return tuple((s[0], s[1]) for _ in range(n_ing))
        if len(s) != n_ing:
            raise ValueError(
                f"workload {self.name!r}: {len(s)} per-ingress stream pairs "
                f"for a fleet with {n_ing} ingresses")
        out = []
        for pair in s:
            if len(pair) != 2:
                raise ValueError(
                    f"workload {self.name!r}: each ingress needs an "
                    "(inference, training) StreamSpec pair")
            out.append((pair[0], pair[1]))
        return tuple(out)

    def mean_rate(self, n_ing: int) -> float:
        """Aggregate arrivals/s across all streams (auto_queue_cap input)."""
        return sum(st.mean_rate()
                   for pair in self.resolve(n_ing) for st in pair)


# ---------------------------------------------------------------------------
# JSON spec files (scripts/validate_workload.py lints these)
# ---------------------------------------------------------------------------

def _stream_from_dict(d: dict, where: str) -> StreamSpec:
    known = {"kind", "rate", "amp", "period", "phase_s", "times", "sizes",
             "rates", "bin_s", "periodic"}
    unknown = set(d) - known
    if unknown:
        raise ValueError(f"{where}: unknown stream keys {sorted(unknown)}")
    kw = dict(d)
    for arr_key in ("times", "sizes", "rates"):
        if kw.get(arr_key) is not None:
            kw[arr_key] = np.asarray(kw[arr_key], np.float64)
    return StreamSpec(**kw)


def workload_from_dict(doc: dict, n_ing: Optional[int] = None) -> WorkloadSpec:
    """Build a WorkloadSpec from a parsed JSON document.

    Schema (docs/workloads.md):

    .. code-block:: json

        {"name": "...",
         "streams": {"inference": {...}, "training": {...}}
          | [{"ingress": "gw-..." | 0, "inference": {...}, "training": {...}}],
         "signals": {"price": [...], "carbon": [[...]] ,
                     "bin_s": 3600, "periodic": true, "observe": false}}

    The list form needs ``n_ing`` (and covers every ingress exactly
    once when entries carry integer indices; `load_workload_json`
    resolves ingress *names* against a fleet first).
    """
    known = {"name", "streams", "signals"}
    unknown = set(doc) - known
    if unknown:
        raise ValueError(f"unknown top-level keys {sorted(unknown)}")
    if "streams" not in doc:
        raise ValueError("spec needs a 'streams' section")
    name = doc.get("name", "custom")
    raw = doc["streams"]
    if isinstance(raw, dict):
        unknown = set(raw) - {"inference", "training"}
        if unknown:
            raise ValueError(
                f"{name}: unknown stream-section keys {sorted(unknown)} "
                "(expected 'inference'/'training' — a typo here would "
                "silently drop the stream)")
        streams = (
            _stream_from_dict(raw.get("inference", {"kind": "off"}),
                              f"{name}/inference"),
            _stream_from_dict(raw.get("training", {"kind": "off"}),
                              f"{name}/training"),
        )
    else:
        if n_ing is None:
            raise ValueError("per-ingress stream list needs the fleet shape "
                             "(n_ing) to resolve against")
        pairs = [None] * n_ing
        for i, entry in enumerate(raw):
            unknown = set(entry) - {"ingress", "inference", "training"}
            if unknown:
                raise ValueError(
                    f"{name}: stream entry {i} has unknown keys "
                    f"{sorted(unknown)} (expected ingress/inference/"
                    "training)")
            idx = entry.get("ingress", i)
            if not isinstance(idx, int) or not 0 <= idx < n_ing:
                raise ValueError(
                    f"{name}: stream entry {i} has unresolved ingress "
                    f"{entry.get('ingress')!r} (need an index in "
                    f"[0, {n_ing}))")
            if pairs[idx] is not None:
                raise ValueError(f"{name}: duplicate streams for ingress {idx}")
            pairs[idx] = (
                _stream_from_dict(entry.get("inference", {"kind": "off"}),
                                  f"{name}/ing{idx}/inference"),
                _stream_from_dict(entry.get("training", {"kind": "off"}),
                                  f"{name}/ing{idx}/training"),
            )
        off = StreamSpec(kind="off")
        streams = tuple(p if p is not None else (off, off) for p in pairs)
    signals = None
    if doc.get("signals") is not None:
        sd = dict(doc["signals"])
        unknown = set(sd) - {"price", "carbon", "bin_s", "periodic", "observe"}
        if unknown:
            raise ValueError(f"unknown signal keys {sorted(unknown)}")
        for k in ("price", "carbon"):
            if sd.get(k) is not None:
                sd[k] = np.asarray(sd[k], np.float64)
        signals = SignalSpec(**sd)
    return WorkloadSpec(streams=streams, signals=signals, name=name)


def load_workload_json(path: str, fleet=None) -> WorkloadSpec:
    """Load a spec file, resolving ingress names against ``fleet``."""
    with open(path) as f:
        doc = json.load(f)
    n_ing = None
    if fleet is not None:
        n_ing = fleet.n_ing
        raw = doc.get("streams")
        if isinstance(raw, list):
            for entry in raw:
                ing = entry.get("ingress")
                if isinstance(ing, str):
                    if ing not in fleet.ingress_names:
                        raise ValueError(
                            f"{path}: unknown ingress {ing!r}; fleet has "
                            f"{', '.join(fleet.ingress_names)}")
                    entry["ingress"] = fleet.ingress_names.index(ing)
    spec = workload_from_dict(doc, n_ing=n_ing)
    if doc.get("name") is None:
        spec = dataclasses.replace(spec, name=path)
    return spec

"""Time-varying price/carbon signal timelines, sampled in-graph.

A compiled signal set is two device arrays plus static shape facts; a
sample is one clip/mod + one gather — cheap enough to run at every
admission/routing decision and once per accrual interval.  The legacy
static world (`FleetSpec.price_hourly` [24] + constant per-DC
`FleetSpec.carbon`) is expressible exactly: a periodic 24-bin hourly
price timeline samples to ``price_hourly[(t % 86400) // 3600]`` — the
same value every hour-keyed legacy site computed — and a [1, n_dc]
carbon timeline is the constant map.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from .spec import SignalSpec


@dataclasses.dataclass(frozen=True)
class CompiledSignals:
    """Device-resident signal timelines for one (spec, fleet) pair.

    ``price`` [T_p] USD/kWh and ``carbon`` [T_c, n_dc] gCO2/kWh are
    piecewise-constant from t=0, each over its OWN bin width (a spec
    that declares only one half keeps the legacy fallback's native
    resolution for the other — the hourly tariff stays hourly no matter
    what ``bin_s`` the declared half uses); ``periodic`` wraps at the
    timeline length, else the last bin extends forever.  ``observe``
    mirrors the spec (RL observation extension).
    """

    price: jnp.ndarray  # [T_p] f32
    carbon: jnp.ndarray  # [T_c, n_dc] f32
    price_bin_s: float
    carbon_bin_s: float
    price_periodic: bool  # fallback halves wrap regardless of the spec
    carbon_periodic: bool
    observe: bool

    @staticmethod
    def _bin(t, bin_s: float, n_bins: int, periodic: bool):
        # bin in the CLOCK's dtype: casting a float64 week-scale t to f32
        # first would round events within ~16 ms of an hour boundary into
        # the adjacent bin (f32 ulp at t=5e5 is 0.03 s) — the whole point
        # of the long-horizon float64 clock is that it doesn't do that
        idx = jnp.floor(jnp.asarray(t) / bin_s)
        if periodic:
            idx = jnp.mod(idx, n_bins)
        return jnp.clip(idx, 0, n_bins - 1).astype(jnp.int32)

    def price_at(self, t):
        """Scalar USD/kWh at simulated time ``t``."""
        return self.price[self._bin(t, self.price_bin_s,
                                    self.price.shape[0],
                                    self.price_periodic)]

    def carbon_at(self, t):
        """[n_dc] gCO2/kWh at simulated time ``t``."""
        return self.carbon[self._bin(t, self.carbon_bin_s,
                                     self.carbon.shape[0],
                                     self.carbon_periodic)]


def compile_signals(spec: Optional[SignalSpec], fleet) -> Optional[CompiledSignals]:
    """SignalSpec -> CompiledSignals (None spec -> None: signals off).

    Missing halves fall back to the fleet's static tables, so a spec that
    only varies the price keeps the legacy carbon map (and vice versa).
    """
    if spec is None:
        return None
    n_dc = fleet.n_dc
    price_bin_s = carbon_bin_s = float(spec.bin_s)
    price_periodic = carbon_periodic = bool(spec.periodic)
    if spec.price is not None:
        price = np.asarray(spec.price, np.float32).reshape(-1)
    else:
        # legacy fallback keeps its native hourly bins AND daily wrap —
        # resampling the 24-entry tariff onto an arbitrary bin_s (or
        # clamping it at hour 23 for a non-periodic spec) would silently
        # stretch or misalign the day
        price = np.asarray(fleet.price_hourly, np.float32)
        price_bin_s, price_periodic = 3600.0, True
    if spec.carbon is not None:
        carbon = np.asarray(spec.carbon, np.float32)
        if carbon.ndim == 1:
            carbon = carbon[None, :]
        if carbon.shape[-1] != n_dc:
            raise ValueError(
                f"carbon timeline has {carbon.shape[-1]} DC columns for a "
                f"{n_dc}-DC fleet")
    else:
        carbon = np.asarray(fleet.carbon, np.float32)[None, :]
        carbon_bin_s, carbon_periodic = 3600.0, True  # constant map
    return CompiledSignals(
        price=jnp.asarray(price), carbon=jnp.asarray(carbon),
        price_bin_s=price_bin_s, carbon_bin_s=carbon_bin_s,
        price_periodic=price_periodic, carbon_periodic=carbon_periodic,
        observe=bool(spec.observe))


def legacy_signals(fleet, observe: bool = False) -> CompiledSignals:
    """The static paper world as timelines: periodic hourly price +
    constant per-DC carbon.  Samples are value-identical to the legacy
    ``price_hourly[hour]`` / ``carbon[dc]`` sites.  Routed through
    `compile_signals` — THE one construction path the engine uses (a
    second hand-built CompiledSignals could silently drift from it)."""
    return compile_signals(
        SignalSpec(price=np.asarray(fleet.price_hourly, np.float64),
                   carbon=np.asarray(fleet.carbon, np.float64),
                   bin_s=3600.0, periodic=True, observe=observe), fleet)

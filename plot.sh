#!/usr/bin/env bash
# Plot every run directory under $1 (default runs/): the 11-figure multi-run
# comparison plus the 8-figure per-run debug suite. Counterpart of the
# reference's plot.sh.
set -euo pipefail

OUT_ROOT="${1:-runs}"
FIG_DIR="${FIG_DIR:-$OUT_ROOT/figs}"

run_args=()
for d in "$OUT_ROOT"/*/; do
    name="$(basename "$d")"
    [ "$name" = "figs" ] && continue
    [ -f "$d/cluster_log.csv" ] || continue
    run_args+=(--run "$name=$d")
done

if [ "${#run_args[@]}" -eq 0 ]; then
    echo "no runs with cluster_log.csv under $OUT_ROOT" >&2
    exit 1
fi

python plot_sim_result.py "${run_args[@]}" --outdir "$FIG_DIR" "${@:2}"
for d in "$OUT_ROOT"/*/; do
    [ -f "$d/cluster_log.csv" ] || continue
    python plot_single_algo.py --run "$d" --outdir "$d/figs"
done

"""Benchmark: simulated job-steps/sec with RL training in the loop.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

The metric is aggregate simulated events processed per wall-second across a
vmapped batch of chsac_af rollouts with the CHSAC-AF policy acting inside
the scan and SAC gradient steps interleaved — i.e. the full learning
pipeline, not a physics microbench.  The reference publishes no numbers
(BASELINE.md), so vs_baseline compares against the north-star target of
1e6 job-steps/sec (BASELINE.json) scaled to the number of available chips
(the target is quoted for a v5e-8; one chip's fair share is 1/8 of it).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402
import numpy as np  # noqa: E402

# honor an explicit JAX_PLATFORMS=cpu despite the axon plugin's config override
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    jax.config.update("jax_platforms", "cpu")


def main():
    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.parallel import DistributedTrainer, make_mesh

    n_dev = len(jax.devices())
    n_rollouts = int(os.environ.get("BENCH_ROLLOUTS", 128))
    n_rollouts -= n_rollouts % n_dev or 0
    chunk_steps = int(os.environ.get("BENCH_CHUNK", 512))
    n_chunks = int(os.environ.get("BENCH_CHUNKS", 8))

    fleet = build_fleet()
    params = SimParams(
        algo="chsac_af", duration=1e9,  # never finishes inside the bench
        log_interval=20.0,
        inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson", trn_rate=0.1,
        rl_warmup=256, rl_batch=256, job_cap=256, lat_window=512, seed=0,
    )
    trainer = DistributedTrainer(
        fleet, params, n_rollouts=n_rollouts, mesh=make_mesh(),
        replay_capacity_per_shard=50_000, sac_steps_per_chunk=1,
    )

    # compile + warmup
    m = trainer.train_chunk(chunk_steps=chunk_steps)
    ev0 = int(m["n_events"])
    jax.block_until_ready(trainer.states.t)

    t0 = time.perf_counter()
    for _ in range(n_chunks):
        m = trainer.train_chunk(chunk_steps=chunk_steps)
    jax.block_until_ready(trainer.states.t)
    wall = time.perf_counter() - t0

    events = int(m["n_events"]) - ev0
    rate = events / wall
    target = 1e6 * n_dev / 8.0  # north star is quoted for 8 chips
    print(json.dumps({
        "metric": "sim_job_steps_per_sec_rl_in_loop",
        "value": round(rate, 1),
        "unit": "events/sec",
        "vs_baseline": round(rate / target, 4),
    }))


if __name__ == "__main__":
    main()

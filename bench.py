"""Benchmark: simulated job-steps/sec with RL training in the loop.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.

The metric is aggregate simulated events processed per wall-second across a
vmapped batch of chsac_af rollouts with the CHSAC-AF policy acting inside
the scan and SAC gradient steps interleaved — i.e. the full learning
pipeline, not a physics microbench.  The reference publishes no numbers
(BASELINE.md), so vs_baseline compares against the north-star target of
1e6 job-steps/sec (BASELINE.json) scaled to the number of available chips
(the target is quoted for a v5e-8; one chip's fair share is 1/8 of it).

Robustness: the axon TPU tunnel is known to wedge such that `jax.devices()`
HANGS (not errors) for minutes.  The backend is therefore probed in a
subprocess with a hard timeout, with bounded retries + backoff; on
persistent failure the bench degrades to a clearly-labeled CPU fallback
measurement instead of dying with rc=1 (round-1 failure mode, VERDICT.md).

Env knobs: BENCH_ROLLOUTS (256), BENCH_CHUNK (512), BENCH_CHUNKS (8),
BENCH_JOB_CAP (128), BENCH_WARMUP (256; set huge to bench the engine
without SAC updates), BENCH_SWEEP=1 (sweep R x job_cap, report best),
BENCH_PROFILE=DIR (capture a jax.profiler trace of the timed chunks),
BENCH_PROBE_TIMEOUT (120 s), BENCH_PROBE_RETRIES (2), BENCH_WORKLOAD
(1; 0 skips the round-10 trace-replay workload probe), BENCH_COST (1;
0 skips the compiled-program cost-model section — it pays one extra
XLA compile of the primary config), BENCH_TWIN (1; 0 skips the
round-19 twin fork+forecast latency probe).
"""

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

# Public v5e per-chip peaks (cloud.google.com/tpu/docs/v5e): 197 bf16
# TFLOP/s on the MXU, 819 GB/s HBM bandwidth.
V5E_PEAK_BF16_FLOPS = 1.97e14
V5E_HBM_BYTES_PER_S = 8.19e11


def _load_count_step_ops():
    """scripts/count_step_ops.py as a module (shared by the census bank
    and the workload probe — one loader, one protocol)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "count_step_ops", os.path.join(HERE, "scripts",
                                       "count_step_ops.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def flat_eqn_count(jaxpr):
    """Recursively flattened eqn count — the dispatch-bound step's
    first-order cost model.  Delegates to analysis.walker.flat_count:
    ONE flattening rule shared with the ceiling pins
    (tests/test_perf_structure.py), the census, and the linter, so the
    probes below and the perf gates count identically by construction."""
    from distributed_cluster_gpus_tpu.analysis.walker import flat_count

    return flat_count(jaxpr)


def chunk_scan_body(jpr, length=8):
    """The main event-scan body of a traced `_run_chunk(..., length)` —
    the largest length-N scan (the amp>1 pregen fallback would add a
    smaller second one).  Shared core: analysis.walker.main_scan_body."""
    from distributed_cluster_gpus_tpu.analysis.walker import main_scan_body

    return main_scan_body(jpr, length).params["jaxpr"].jaxpr


def cost_model(trainer, chunk_steps, events_per_chunk, measured_ev_s,
               platform, n_dev=1):
    """Analytical per-event cost of the compiled full-pipeline chunk.

    Compiles the trainer's chunk program AOT (`Compiled.cost_analysis()` —
    post-optimization HLO, so fusion is accounted for) and reduces it to
    per-event FLOPs and HBM bytes, the implied single-chip v5e roofline
    events/s (min of the compute- and bandwidth-bound rates), and — when
    the measurement itself ran on the TPU — the achieved MFU / HBM
    utilization / roofline attainment.  Three wedged-tunnel rounds showed
    the bench needs a defensible TPU projection that does not require the
    chip (VERDICT r04 item 1); this is it, with the caveat recorded in the
    JSON: the step program is op-count bound (docs/perf_notes.md), so the
    roofline is an upper bound, not an expectation.
    """
    import jax

    fn = trainer._step_fns[chunk_steps]
    try:
        lowered = fn.lower(trainer.states, trainer.replay, trainer.sac,
                           jax.random.key(0))
        ca = lowered.compile().cost_analysis()
    except Exception as e:  # noqa: BLE001 - evidence-only; never kill the bench
        sys.stderr.write(f"[bench] cost_analysis unavailable: {e!r}\n")
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", -1.0))
    hbm_bytes = float(ca.get("bytes accessed", -1.0))
    if flops <= 0 or hbm_bytes <= 0 or events_per_chunk <= 0:
        sys.stderr.write(f"[bench] cost_analysis degenerate: flops={flops} "
                         f"bytes={hbm_bytes} events={events_per_chunk}\n")
        return None
    # cost_analysis reports the post-SPMD-partitioning PER-DEVICE module
    # cost; events_per_chunk is the global (psum'd) count — divide it down
    # to one device so per-event cost and the per-chip roofline line up
    events_per_dev = events_per_chunk / max(1, n_dev)
    f_ev = flops / events_per_dev
    b_ev = hbm_bytes / events_per_dev
    bound_compute = V5E_PEAK_BF16_FLOPS / f_ev
    bound_bw = V5E_HBM_BYTES_PER_S / b_ev
    out = {
        "compiled_on": platform,
        "chunk_per_device": {
            "flops": flops, "hbm_bytes": hbm_bytes,
            "transcendentals": float(ca.get("transcendentals", 0.0)),
            "events": events_per_dev, "n_devices": n_dev},
        "per_event": {"flops": round(f_ev, 2), "hbm_bytes": round(b_ev, 2)},
        "v5e_roofline_per_chip": {
            "compute_bound_ev_s": round(bound_compute, 1),
            "bandwidth_bound_ev_s": round(bound_bw, 1),
            "binding": "hbm" if bound_bw < bound_compute else "mxu",
            "bound_ev_s": round(min(bound_compute, bound_bw), 1),
        },
        "caveat": "upper bound: the step program is op-count bound "
                  "(many small fused kernels; docs/perf_notes.md), so "
                  "dispatch/fusion overhead, not FLOPs or HBM, sets the "
                  "realized rate",
    }
    if platform in ("tpu", "axon") and measured_ev_s > 0:
        per_chip = measured_ev_s / max(1, n_dev)
        out["measured"] = {
            "ev_s_per_chip": round(per_chip, 1),
            "mfu": round(per_chip * f_ev / V5E_PEAK_BF16_FLOPS, 6),
            "hbm_utilization": round(per_chip * b_ev / V5E_HBM_BYTES_PER_S, 6),
            "roofline_attainment": round(
                per_chip / min(bound_compute, bound_bw), 6),
        }
    return out


def probe_tpu(timeout_s: float, retries: int, backoff_s: float = 30.0):
    """Probe the default JAX backend in a subprocess (it may hang, not fail).

    Returns (n_devices, platform) or (0, None) after exhausting retries.
    """
    code = ("import jax; d = jax.devices(); "
            "print(len(d), d[0].platform)")
    for attempt in range(retries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                timeout=timeout_s, text=True)
            if out.returncode == 0 and out.stdout.strip():
                n, platform = out.stdout.split()[:2]
                return int(n), platform
            sys.stderr.write(f"[bench] probe attempt {attempt + 1} rc="
                             f"{out.returncode}: {out.stderr[-300:]}\n")
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"[bench] probe attempt {attempt + 1} timed out "
                             f"after {timeout_s:.0f}s (wedged tunnel?)\n")
        if attempt + 1 < retries:
            time.sleep(backoff_s * (attempt + 1))
    return 0, None


def _make_trainer(n_rollouts: int, job_cap: int, queue_mode=None,
                  queue_cap=None, warmup=None):
    """Build the bench trainer (the full chsac_af learning pipeline).

    The keyword overrides exist for `cost_model_compile_only`: the
    north-star projection must be the canonical ring-layout learning
    pipeline even when the invoking stage's BENCH_* env asks for an
    ablated one."""
    import jax

    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.parallel import DistributedTrainer, make_mesh

    n_dev = len(jax.devices())
    n_rollouts = max(n_dev, n_rollouts - n_rollouts % n_dev)

    fleet = build_fleet()
    # BENCH_WARMUP: set huge (e.g. 2000000000) to keep SAC gated off and
    # measure the engine+ingest path alone (ablation for profiling)
    params = SimParams(
        algo="chsac_af", duration=1e9,  # never finishes inside the bench
        log_interval=20.0,
        inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson", trn_rate=0.1,
        rl_warmup=int(os.environ.get("BENCH_WARMUP", 256)
                      if warmup is None else warmup),
        rl_batch=256, job_cap=job_cap, lat_window=512, seed=0,
        # round-4 queue rings: waiting jobs leave the slab, so job_cap
        # bounds only PLACED jobs.  BENCH_QUEUE_MODE=slab restores the
        # round-3 all-in-slab layout for the on-chip A/B.
        queue_mode=queue_mode or os.environ.get("BENCH_QUEUE_MODE", "ring"),
        queue_cap=int(os.environ.get("BENCH_QUEUE_CAP", 512)
                      if queue_cap is None else queue_cap),
    )
    trainer = DistributedTrainer(
        fleet, params, n_rollouts=n_rollouts, mesh=make_mesh(),
        replay_capacity_per_shard=50_000, sac_steps_per_chunk=1,
    )
    return trainer, n_rollouts, n_dev


def cost_model_compile_only(n_rollouts: int, chunk_steps: int, job_cap: int,
                            platform: str):
    """North-star-shape cost model without running it (wedged-tunnel path).

    The CPU fallback measurement shrinks to R=32/J=128 for liveness, but
    the projection the round needs is for the north-star configuration —
    compile it (every scan step fires exactly one event per live rollout,
    so events/chunk = R * chunk_steps without running).  Queue layout and
    warmup are pinned to the canonical pipeline regardless of the invoking
    stage's BENCH_* ablation env."""
    trainer, n_rollouts, n_dev = _make_trainer(
        n_rollouts, job_cap, queue_mode="ring", queue_cap=512, warmup=256)
    trainer._step_fns[chunk_steps] = trainer._build_step(chunk_steps)
    cm = cost_model(trainer, chunk_steps, n_rollouts * chunk_steps, 0.0,
                    platform, n_dev)
    if cm:
        cm["projection_only"] = True
        cm["config"] = {"rollouts": n_rollouts, "job_cap": job_cap,
                        "chunk_steps": chunk_steps}
    return cm


def measure(n_rollouts: int, chunk_steps: int, n_chunks: int, job_cap: int,
            profile_dir=None, with_cost=False, platform=None):
    """One bench configuration -> (events/sec, events, wall s, cost model)."""
    import jax

    trainer, n_rollouts, n_dev = _make_trainer(n_rollouts, job_cap)

    # compile + warmup
    m = trainer.train_chunk(chunk_steps=chunk_steps)
    ev0 = int(m["n_events"])
    jax.block_until_ready(trainer.states.t)

    import contextlib

    ctx = contextlib.nullcontext()
    if profile_dir:
        from distributed_cluster_gpus_tpu.obs.trace import trace

        ctx = trace(profile_dir)
    with ctx:
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            m = trainer.train_chunk(chunk_steps=chunk_steps)
        jax.block_until_ready(trainer.states.t)
        wall = time.perf_counter() - t0

    events = int(m["n_events"]) - ev0
    cm = None
    if with_cost:
        cm = cost_model(trainer, chunk_steps, events / n_chunks,
                        events / wall,
                        platform or jax.devices()[0].platform, n_dev)
        if cm:
            cm["config"] = {"rollouts": n_rollouts, "job_cap": job_cap,
                            "chunk_steps": chunk_steps}
    return events / wall, events, wall, cm


def best_prior_on_chip(root=None):
    """Best on-chip measurement already captured this round, if any.

    The recovery suite (scripts/tpu_recovery.sh) banks on-chip JSONs as the
    tunnel allows; when the round-end bench lands in a wedged window its CPU
    fallback cross-references the strongest prior on-chip evidence instead
    of silently superseding it.  Only the full-pipeline runs (key/sweep)
    are comparable to this bench's metric — the ablations (no-SAC, scatter,
    nopregen, chunk2048) measure deliberately different pipelines and must
    not be cited as the headline prior.

    Delegates to the perf ledger's loader
    (`analysis.ledger.best_prior_on_chip`): ONE round-discovery rule
    shared with scripts/perf_ledger.py and summarize_bench.py, with
    missing/corrupt files folded into one summary line, never a
    traceback — this runs on the degraded-resilience path."""
    from distributed_cluster_gpus_tpu.analysis import ledger

    best, skipped = ledger.best_prior_on_chip(root or HERE)
    if skipped:
        sys.stderr.write(
            "[bench] prior-evidence files skipped: "
            + ", ".join(f"{rel} ({why})" for rel, why in skipped) + "\n")
    return best


def superstep_sweep(chunk_steps=512, n_rollouts=32, job_cap=128,
                    warm_chunks=6, timed_chunks=2, reps=3,
                    algo="joint_nf"):
    """K in {1, 2, 4, 8} superstep sweep of the raw engine (round 6).

    Measures aggregate events/sec over a vmapped batch at the bench shape
    (R=32, J=128) for the heuristic engine — chsac_af is statically
    superstep-ineligible (every event raises a policy-tail request), so
    the coalescing lever is benched on the canonical non-RL optimizer.
    Interleaved repeats with a median keep one CPU-contention spike from
    crowning the wrong K.  Each row also records the STRUCTURAL metric
    the perf tests pin: flattened step-body eqns / K, the per-event op
    count of the compiled program (the step is dispatch-bound, so this is
    the first-order cost model).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.parallel.rollout import batched_init
    from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

    fleet = build_fleet()
    runs, eqns = {}, {}
    for k in (1, 2, 4, 8):
        params = SimParams(
            algo=algo, duration=1e9, log_interval=20.0,
            inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
            trn_rate=0.1, job_cap=job_cap, lat_window=512, seed=0,
            queue_mode="ring", queue_cap=256, superstep_k=k)
        eng = Engine(fleet, params)
        st1 = init_state(jax.random.key(0), fleet, params)
        jpr = jax.make_jaxpr(lambda s, e=eng: e._run_chunk(s, None, 8))(st1)
        eqns[k] = flat_eqn_count(chunk_scan_body(jpr))
        states = batched_init(fleet, params, n_rollouts)
        run = jax.jit(jax.vmap(
            lambda s, e=eng: e._run_chunk(s, None, chunk_steps)[0]))
        for _ in range(warm_chunks):  # compile + reach steady state
            states = run(states)
        jax.block_until_ready(states.t)
        runs[k] = (run, states)

    rates = {k: [] for k in runs}
    ev_iter = {k: [] for k in runs}
    for _ in range(reps):
        for k in runs:
            run, states = runs[k]
            ev0 = int(np.sum(np.asarray(states.n_events)))
            t0 = time.perf_counter()
            for _ in range(timed_chunks):
                states = run(states)
            jax.block_until_ready(states.t)
            wall = time.perf_counter() - t0
            ev = int(np.sum(np.asarray(states.n_events))) - ev0
            runs[k] = (run, states)
            rates[k].append(ev / wall)
            ev_iter[k].append(ev / (timed_chunks * chunk_steps * n_rollouts))

    rows = []
    base_rate = sorted(rates[1])[len(rates[1]) // 2]
    for k in sorted(rates):
        med = sorted(rates[k])[len(rates[k]) // 2]
        # median ev/iter too — the window-fill rate drifts as the sim
        # advances, and the banked pair must describe the same reps
        med_ei = sorted(ev_iter[k])[len(ev_iter[k]) // 2]
        # realized vs structural (round 7): the structural speedup is the
        # per-event eqn-count ratio (eqns1 / (eqnsK / K)) — the first-
        # order model of the dispatch-bound step; realized is measured
        # events/s.  Their ratio says how much of the structural curve
        # the compiled program actually delivers (the round-6 two-lane
        # cond left it at ~0.35 at K=8; the select-free body closes it).
        structural = eqns[1] / (eqns[k] / k)
        realized = med / max(base_rate, 1e-9)
        rows.append({
            "superstep_k": k,
            "events_per_sec": round(med, 1),
            "events_per_iteration": round(med_ei, 3),
            # window fill: mean applied-prefix length over K — the
            # first-class number perf_notes used to hand-quote ("fill
            # 2.9/4"); the ledger tracks it per round
            "fill": round(med_ei / k, 4),
            "step_body_eqns": eqns[k],
            "eqns_per_event": round(eqns[k] / k, 1),
            "realized_speedup": round(realized, 4),
            "structural_speedup": round(structural, 4),
            "realized_vs_structural": round(realized / structural, 4),
        })
        sys.stderr.write(
            f"[bench] superstep K={k}: {med:,.0f} ev/s, "
            f"{med_ei:.2f} ev/iter, {eqns[k] / k:.0f} eqns/event, "
            f"realized/structural {realized / structural:.2f}\n")
    return {"algo": algo, "shape": {"rollouts": n_rollouts,
                                    "job_cap": job_cap,
                                    "chunk_steps": chunk_steps},
            "rows": rows}


def obs_overhead_probe(chunk_steps=512, n_rollouts=32, job_cap=128,
                       warm_chunks=6, timed_chunks=2, reps=3,
                       superstep_k=4, algo="joint_nf"):
    """Telemetry cost: events/s with obs off vs on at the bench shape.

    Same harness as :func:`superstep_sweep` (vmapped raw engine, R=32,
    J=128, interleaved repeats, medians) at the canonical K so the
    banked number answers the question operators actually ask: what does
    leaving telemetry on cost?  Also records the structural half — the
    flattened step-body eqn counts of both programs — since the step is
    dispatch-bound and the acceptance gate (docs/observability.md) is
    <= 5% ev/s regression at K=4.
    """
    import dataclasses

    import jax
    import numpy as np

    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.parallel.rollout import batched_init
    from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

    fleet = build_fleet()
    base = SimParams(
        algo=algo, duration=1e9, log_interval=20.0,
        inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
        trn_rate=0.1, job_cap=job_cap, lat_window=512, seed=0,
        queue_mode="ring", queue_cap=256, superstep_k=superstep_k)
    runs, eqns = {}, {}
    for obs_on in (False, True):
        params = dataclasses.replace(base, obs_enabled=obs_on)
        eng = Engine(fleet, params)
        st1 = init_state(jax.random.key(0), fleet, params)
        jpr = jax.make_jaxpr(lambda s, e=eng: e._run_chunk(s, None, 8))(st1)
        eqns[obs_on] = flat_eqn_count(chunk_scan_body(jpr))
        states = batched_init(fleet, params, n_rollouts)
        run = jax.jit(jax.vmap(
            lambda s, e=eng: e._run_chunk(s, None, chunk_steps)[0]))
        for _ in range(warm_chunks):
            states = run(states)
        jax.block_until_ready(states.t)
        runs[obs_on] = (run, states)

    rates = {k: [] for k in runs}
    for _ in range(reps):
        for k in runs:
            run, states = runs[k]
            ev0 = int(np.sum(np.asarray(states.n_events)))
            t0 = time.perf_counter()
            for _ in range(timed_chunks):
                states = run(states)
            jax.block_until_ready(states.t)
            wall = time.perf_counter() - t0
            ev = int(np.sum(np.asarray(states.n_events))) - ev0
            runs[k] = (run, states)
            rates[k].append(ev / wall)

    med = {k: sorted(v)[len(v) // 2] for k, v in rates.items()}
    overhead = 1.0 - med[True] / max(med[False], 1e-9)
    sys.stderr.write(
        f"[bench] obs overhead K={superstep_k}: off {med[False]:,.0f} ev/s, "
        f"on {med[True]:,.0f} ev/s ({overhead * 100:+.1f}% cost), "
        f"eqns {eqns[False]} -> {eqns[True]}\n")
    return {
        "algo": algo,
        "shape": {"rollouts": n_rollouts, "job_cap": job_cap,
                  "chunk_steps": chunk_steps, "superstep_k": superstep_k},
        "events_per_sec_obs_off": round(med[False], 1),
        "events_per_sec_obs_on": round(med[True], 1),
        "overhead_fraction": round(overhead, 4),
        "step_body_eqns_obs_off": eqns[False],
        "step_body_eqns_obs_on": eqns[True],
    }


def io_overlap_probe(chunk_steps=2048, duration=2000.0, superstep_k=4,
                     algo="joint_nf"):
    """Measure the pipelined run_simulation's host/device overlap (round 7).

    Runs one CSV-writing single-rollout simulation through the pipelined
    loop and reports the PhaseTimer split: "rollout" (waiting on device
    compute), "io" (emission fetch + writer handoff — the only io left
    on the critical path) and "io_render" (CSV render+write seconds the
    background writer hid behind device compute).  ``overlap_fraction``
    is io_render / (io_render + io), the share of total host io off the
    critical path — the serial loop's value is 0 by construction.
    """
    import shutil
    import tempfile
    import time as _time

    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.sim.io import run_simulation
    from distributed_cluster_gpus_tpu.obs.trace import PhaseTimer

    fleet = build_fleet()
    params = SimParams(
        algo=algo, duration=duration, log_interval=5.0,
        inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson", trn_rate=0.1,
        job_cap=128, lat_window=512, seed=0, queue_mode="ring",
        queue_cap=1024, superstep_k=superstep_k)
    out = tempfile.mkdtemp(prefix="dcg_io_overlap_")
    timer = PhaseTimer()
    try:
        t0 = _time.perf_counter()
        state = run_simulation(fleet, params, out_dir=out,
                               chunk_steps=chunk_steps, timer=timer)
        wall = _time.perf_counter() - t0
        io_s = timer.totals.get("io", 0.0)
        render_s = timer.totals.get("io_render", 0.0)
        # device-side wall = dispatch + rollout: where it lands depends on
        # the backend (CPU blocks inside the dispatch call; accelerators
        # return instantly and the time shows up in the rollout wait) —
        # report the sum so the split is backend-agnostic
        compute_s = (timer.totals.get("dispatch", 0.0)
                     + timer.totals.get("rollout", 0.0))
        return {
            "config": {"algo": algo, "superstep_k": superstep_k,
                       "chunk_steps": chunk_steps, "duration": duration},
            "wall_s": round(wall, 3),
            "compute_s": round(compute_s, 3),
            "rollout_s": round(timer.totals.get("rollout", 0.0), 3),
            "dispatch_s": round(timer.totals.get("dispatch", 0.0), 3),
            "io_s": round(io_s, 3),
            "io_render_s": round(render_s, 3),
            "overlap_fraction": round(
                render_s / max(render_s + io_s, 1e-9), 4),
            "events": int(state.n_events),
        }
    finally:
        shutil.rmtree(out, ignore_errors=True)


def workload_probe(chunk_steps=512, n_rollouts=32, job_cap=128,
                   warm_chunks=4, timed_chunks=2, reps=3):
    """Trace-replay workload throughput: the flash-crowd preset ev/s.

    Round-10 probe (workload/ subsystem): vmapped raw-engine harness at
    the bench shape running the `flash_crowd` rate-timeline scenario
    WITH price/carbon signal timelines — the production-shaped workload
    path (pregen tables + signal sampling + cost/carbon accrual) at the
    default K=1 (the round-12 superstep A/B for this config lives in
    :func:`fastpath_ab_probe`).  Banks the realized ev/s next to the
    structural half: the step-body eqn count and its `while` census —
    the workload compiler's contract is ZERO while primitives in the
    step body (the thinning loop lives ahead of the scan now), so a
    nonzero count here flags the regression before a golden does.
    """
    import dataclasses

    import jax
    import numpy as np

    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.parallel.rollout import batched_init
    from distributed_cluster_gpus_tpu.sim.engine import Engine
    from distributed_cluster_gpus_tpu.workload import make_preset

    fleet = build_fleet()
    wl = make_preset("flash_crowd", fleet, base_rate=6.0, spike_mult=10.0,
                     horizon_s=7200.0, bin_s=300.0)
    params = SimParams(
        algo="carbon_cost", duration=1e9, log_interval=20.0,
        workload=wl, job_cap=job_cap, lat_window=512, seed=0,
        queue_mode="ring", queue_cap=1024)
    eng = Engine(fleet, params)

    # structural half: flattened step-body eqns + per-class census
    census_mod = _load_count_step_ops()
    from distributed_cluster_gpus_tpu.sim.engine import init_state

    st1 = init_state(jax.random.key(0), fleet, params,
                     workload=eng.workload)
    jpr = jax.make_jaxpr(lambda s: eng._run_chunk(s, None, 8))(st1)
    census = census_mod.op_census(chunk_scan_body(jpr))

    states = batched_init(fleet, params, n_rollouts,
                          workload=eng.workload)
    run = jax.jit(jax.vmap(
        lambda s: eng._run_chunk(s, None, chunk_steps)[0]))
    for _ in range(warm_chunks):
        states = run(states)
    jax.block_until_ready(states.t)
    rates = []
    for _ in range(reps):
        ev0 = int(np.sum(np.asarray(states.n_events)))
        t0 = time.perf_counter()
        for _ in range(timed_chunks):
            states = run(states)
        jax.block_until_ready(states.t)
        wall = time.perf_counter() - t0
        rates.append((int(np.sum(np.asarray(states.n_events))) - ev0)
                     / wall)
    med = sorted(rates)[len(rates) // 2]
    cost = float(np.sum(np.asarray(states.signals.cost_usd)))
    sys.stderr.write(
        f"[bench] workload probe (flash_crowd + signals): {med:,.0f} ev/s, "
        f"step body {census['eqns']} eqns, while={census['while']}, "
        f"accrued {cost:,.2f} USD\n")
    return {
        "preset": "flash_crowd",
        "algo": "carbon_cost",
        "shape": {"rollouts": n_rollouts, "job_cap": job_cap,
                  "chunk_steps": chunk_steps},
        "events_per_sec": round(med, 1),
        "step_body_eqns": census["eqns"],
        "step_body_while": census["while"],
        "census": census,
        "accrued_cost_usd": round(cost, 2),
    }


def fastpath_ab_probe(chunk_steps=512, n_rollouts=32, job_cap=128,
                      warm_chunks=4, timed_chunks=2, reps=3):
    """Round-12 fast-path A/B: legacy vs planner/superstep, per family.

    Same-process INTERLEAVED pairs (alternating timed reps, medians —
    the round-9 planner_ab methodology, noise floor ~1%) for the four
    families round 12 made fast-path eligible:

    * chsac+elastic — planner vs forced-legacy dispatch at K=1 (the
      superstep residue keeps RL singleton);
    * bandit — planner vs forced-legacy at K=1;
    * fault — planner vs forced-legacy at K=1, AND the K=4 superstep
      program vs the K=1 singleton (both planner-on: the round-12
      headline, chaos campaigns on the fused body);
    * signal (price/carbon timelines riding a workload preset) — K=4 vs
      K=1 (the fused body now accrues the cost integral, so --workload
      presets get the superstep).  Three rows: joint_nf + flash_crowd
      (the headline — fuses at the r07 rate, mean L ≈ 2.6),
      carbon_cost + legacy_signals (fuses at L ≈ 3.1), and eco_route
      (the honest near-null: eco scores concentrate load on the
      cheapest DC, so finish events cluster per-DC and same-DC finishes
      do not commute — mean L ≈ 1.5 by the algorithm's own design).

    Each row banks the realized ev/s pair next to the structural half
    (flattened step-body eqns of both programs).  Banked as
    ``bench_results/fastpath_r12.json`` (``python bench.py --fastpath``);
    scripts/summarize_bench.py renders the table.
    """
    import jax
    import numpy as np

    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.models import FaultParams, SimParams
    from distributed_cluster_gpus_tpu.parallel.rollout import batched_init
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)
    from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
    from distributed_cluster_gpus_tpu.workload import make_preset

    fleet = build_fleet()
    base = dict(duration=1e9, log_interval=20.0, inf_mode="sinusoid",
                inf_rate=6.0, trn_mode="poisson", trn_rate=0.1,
                job_cap=job_cap, lat_window=512, seed=0,
                queue_mode="ring", queue_cap=256)
    # sparse, staggered chaos with room to drain between windows: a
    # saturated fleet (the first cut used a 6-DC rolling blackout at
    # trn_rate=1.0) keeps PREEMPTED backlog and non-empty queues alive,
    # which the commutation predicate rightly refuses to fuse — the K=4
    # arm then measures the saturation, not the program.  Window times
    # are early: the fleet aggregates ~146 events per SIM second, so
    # the warm+timed chunks only cover t ≈ 0-35 s (K=1) / 0-90 s (K=4)
    # of sim time — chaos must land inside that span to be real.
    faults = FaultParams(
        outages=((1, 5.0, 9.0), (4, 15.0, 19.0), (2, 26.0, 30.0)),
        derates=((3, 10.0, 20.0, 0.6),),
        wan=((0, 2, 3.0, 8.0, 3.0, 0.1),))

    def build(algo, k=1, force_legacy=False, fault=False, signal=None,
              elastic=False, eco_objective=None):
        kw = dict(base, algo=algo, superstep_k=k)
        if eco_objective is not None:
            kw["eco_objective"] = eco_objective
        if fault:
            kw["faults"] = faults
        if signal == "flash":
            kw["workload"] = make_preset(
                "flash_crowd", fleet, base_rate=6.0, spike_mult=4.0,
                horizon_s=1800.0, bin_s=100.0)
        elif signal == "legacy":
            # the legacy arrival process with the legacy price/carbon
            # tables lifted into explicit timelines — the exact r07
            # superstep shape, plus signal accrual
            kw["workload"] = make_preset(
                "legacy_signals", fleet, params=SimParams(**base))
        if elastic:
            kw["elastic_scaling"] = True
        params = SimParams(**kw)
        pp = None
        if algo == "chsac_af":
            cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc),
                            n_dc=fleet.n_dc,
                            n_g=params.max_gpus_per_job,
                            constraints=default_constraints(500.0))
            pp = sac_init(cfg, jax.random.key(1))
            eng = Engine(fleet, params, policy_apply=make_policy_apply(cfg))
        else:
            eng = Engine(fleet, params)
        if force_legacy:
            assert eng.planner_on, "forced-gate A/B needs an eligible config"
            eng.planner_on = False
        st1 = init_state(jax.random.key(0), fleet, params,
                         workload=eng.workload)
        jpr = jax.make_jaxpr(
            lambda s, p=pp, e=eng: e._run_chunk(s, p, 8))(st1)
        eqns = flat_eqn_count(chunk_scan_body(jpr))
        states = batched_init(fleet, params, n_rollouts,
                              workload=eng.workload)
        run = jax.jit(jax.vmap(
            lambda s, p=pp, e=eng: e._run_chunk(s, p, chunk_steps)[0]))
        for _ in range(warm_chunks):
            states = run(states)
        jax.block_until_ready(states.t)
        return {"run": run, "states": states, "eqns": eqns}

    def ab(fast, legacy):
        """Interleaved timed reps; returns (fast ev/s, legacy ev/s)."""
        rates = {"fast": [], "legacy": []}
        pair = {"fast": fast, "legacy": legacy}
        for _ in range(reps):
            for name, v in pair.items():
                states = v["states"]
                ev0 = int(np.sum(np.asarray(states.n_events)))
                t0 = time.perf_counter()
                for _ in range(timed_chunks):
                    states = v["run"](states)
                jax.block_until_ready(states.t)
                wall = time.perf_counter() - t0
                v["states"] = states
                rates[name].append(
                    (int(np.sum(np.asarray(states.n_events))) - ev0)
                    / wall)
        return tuple(sorted(rates[n])[reps // 2] for n in ("fast",
                                                           "legacy"))

    cases = [
        # (row name, mode, k, fast kwargs, legacy kwargs)
        ("chsac_elastic", "planner", 1,
         dict(algo="chsac_af", elastic=True),
         dict(algo="chsac_af", elastic=True, force_legacy=True)),
        ("bandit", "planner", 1,
         dict(algo="bandit"),
         dict(algo="bandit", force_legacy=True)),
        ("fault", "planner", 1,
         dict(algo="default_policy", fault=True),
         dict(algo="default_policy", fault=True, force_legacy=True)),
        ("fault", "superstep", 4,
         dict(algo="default_policy", fault=True, k=4),
         dict(algo="default_policy", fault=True, k=1)),
        # joint_nf under the flash-crowd preset (signal timelines + cost
        # accrual in the fused body) is the headline: it fuses at mean
        # L ≈ 2.6, the r07 rate.  carbon_cost rides the legacy-signals
        # preset (fuses at L ≈ 3.1 there — its admission holds queues
        # only under heavier load).  eco_route is the honest near-null:
        # eco scores concentrate load on the cheapest DC, finish events
        # cluster per-DC, and same-DC finishes do not commute (mean
        # L ≈ 1.5) — an algorithmic property, not an eligibility bug.
        ("signal", "superstep", 4,
         dict(algo="joint_nf", signal="flash", k=4),
         dict(algo="joint_nf", signal="flash", k=1)),
        ("signal_carbon", "superstep", 4,
         dict(algo="carbon_cost", signal="legacy", k=4),
         dict(algo="carbon_cost", signal="legacy", k=1)),
        ("signal_eco", "superstep", 4,
         dict(algo="eco_route", signal="legacy", k=4, eco_objective="cost"),
         dict(algo="eco_route", signal="legacy", k=1, eco_objective="cost")),
    ]
    rows = []
    for name, mode, k, fast_kw, legacy_kw in cases:
        fast = build(**fast_kw)
        legacy = build(**legacy_kw)
        f_ev, l_ev = ab(fast, legacy)
        row = {
            "config": name, "mode": mode, "k": k,
            "algo": fast_kw["algo"],
            "fast_ev_s": round(f_ev, 1), "legacy_ev_s": round(l_ev, 1),
            "speedup": round(f_ev / max(l_ev, 1e-9), 4),
            "fast_eqns": fast["eqns"], "legacy_eqns": legacy["eqns"],
        }
        if mode == "superstep":
            row["fast_eqns_per_event"] = round(fast["eqns"] / k, 1)
        if fast_kw.get("fault"):
            # prove the chaos was real inside the measured window (the
            # first cut staged its windows past the ~35 s of sim time
            # the chunks cover, silently measuring a fault-free run)
            row["fast_preempted"] = int(np.sum(np.asarray(
                fast["states"].fault.n_preempted)))
            row["fast_migrated"] = int(np.sum(np.asarray(
                fast["states"].fault.n_migrated)))
            assert row["fast_preempted"] > 0, (
                f"{name}: no preemptions — the fault windows missed the "
                "simulated span")
        rows.append(row)
        sys.stderr.write(
            f"[bench] fastpath {name}/{mode} K={k}: fast {f_ev:,.0f} "
            f"ev/s vs legacy {l_ev:,.0f} ev/s "
            f"({row['speedup']:.3f}x), eqns {legacy['eqns']} -> "
            f"{fast['eqns']}\n")
    return {
        "note": ("round-12 fast-path eligibility A/B: interleaved "
                 "same-process legacy-vs-planner/superstep medians "
                 "(round-9 planner_ab methodology, ~1% noise floor); "
                 "planner rows force Engine.planner_on=False for the "
                 "legacy arm, superstep rows compare the K=4 program "
                 "against the K=1 singleton with the planner on in "
                 "both arms"),
        "shape": {"rollouts": n_rollouts, "job_cap": job_cap,
                  "chunk_steps": chunk_steps, "reps": reps,
                  "timed_chunks": timed_chunks},
        "rows": rows,
    }


def sweep_grid_probe(duration=120.0, chunk_steps=512, reps=3):
    """Round-16 sweep-grid A/B: the bucketed one-program grid vs the
    serial per-cell loop, same cells, interleaved medians.

    A 16-cell duo-fleet scenario grid (4 outage rates x 2 algorithms x
    2 seeds — the tests/test_sweep.py golden shape, scaled up) runs
    through both drivers: the grid arm buckets cells by compiled-program
    signature and runs each bucket as ONE ``jit(vmap(...))`` loop
    (``sweep.run_bucket``); the serial arm is the legacy chaos_sweep
    path, one ``run_algo`` dispatch sequence per cell.  Arms alternate
    timed reps (the round-9/round-12 interleaved methodology, ~1% noise
    floor) and report median cells/s plus aggregate ev/s — the rows are
    bit-identical by construction (asserted), so this measures pure
    dispatch amortization, which on CPU is the wall
    (``bench_results/attrib_r14.json``).  Banked as
    ``bench_results/sweep_r16.json`` (``python bench.py --sweep-grid``);
    scripts/summarize_bench.py renders the table and analysis/ledger.py
    ingests it as the ``sweep_grid`` record kind.
    """
    from distributed_cluster_gpus_tpu import sweep
    from distributed_cluster_gpus_tpu.evaluation import run_algo
    from distributed_cluster_gpus_tpu.sweep.compiler import (
        bucket_cells, cell_params, run_bucket)
    from distributed_cluster_gpus_tpu.sweep.spec import (
        cell_fault_params, grid_base, grid_cells)

    grid = sweep.SweepGrid(axis="rates", rates=(0.0, 0.5, 1.0, 2.0),
                           algos=("default_policy", "eco_route"),
                           seeds=(123, 124), fleet="duo",
                           duration=duration)
    fleet, base = grid_base(grid)
    cells = grid_cells(grid)
    fp = cell_fault_params(grid, cells)

    def grid_arm():
        rows, events = [], 0
        buckets = bucket_cells(fleet, base, cells, fp)
        for b in buckets:
            rows += run_bucket(b, chunk_steps=chunk_steps)
            events += b.events
        return rows, events, len(buckets)

    def serial_arm():
        rows = []
        for c in cells:
            p = cell_params(base, c, fp[c])
            row = run_algo(fleet, p, chunk_steps=chunk_steps).row()
            row.update(c.row_id())
            rows.append(row)
        return rows

    # warm rep: compiles land in the persistent cache and stay hot in
    # the in-process jit caches for the timed reps — and it doubles as
    # the correctness assertion (grid rows == serial rows, bit-for-bit)
    g_rows, events, n_buckets = grid_arm()
    s_rows = serial_arm()
    gk = {sweep.cell_key(r): json.dumps(r, sort_keys=True) for r in g_rows}
    sk = {sweep.cell_key(r): json.dumps(r, sort_keys=True) for r in s_rows}
    assert gk == sk, "sweep grid probe: grid rows diverge from serial rows"

    walls = {"grid": [], "serial": []}
    for _ in range(reps):
        t0 = time.perf_counter()
        grid_arm()
        walls["grid"].append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        serial_arm()
        walls["serial"].append(time.perf_counter() - t0)
    gw = sorted(walls["grid"])[reps // 2]
    sw = sorted(walls["serial"])[reps // 2]
    n = len(cells)
    sys.stderr.write(
        f"[bench] sweep grid: {n} cells in {n_buckets} buckets — grid "
        f"{n / gw:.2f} cells/s vs serial {n / sw:.2f} cells/s "
        f"({sw / gw:.2f}x)\n")
    return {
        "note": ("round-16 sweep-grid A/B: bucketed one-program grid vs "
                 "serial per-cell run_algo, identical cells "
                 "(bit-identical rows asserted), interleaved timed reps, "
                 "medians; cells/s is the dispatch-amortization "
                 "headline, ev/s the shared-events aggregate"),
        "fleet": "duo", "n_cells": n, "n_buckets": n_buckets,
        "reps": reps, "duration_s": duration, "chunk_steps": chunk_steps,
        "axes": {"rates": list(grid.rates), "algos": list(grid.algos),
                 "seeds": list(grid.seeds)},
        "events_total": events, "rows_bit_identical": True,
        "grid_wall_s": round(gw, 3), "serial_wall_s": round(sw, 3),
        "grid_cells_s": round(n / gw, 3),
        "serial_cells_s": round(n / sw, 3),
        "grid_ev_s": round(events / gw, 1),
        "serial_ev_s": round(events / sw, 1),
        "speedup_cells": round(sw / gw, 4),
    }


def _twin_probe_base_doc(n_events=4096, rate=6.0, seed=7):
    """Deterministic trace workload for the twin probe: numpy-generated
    exponential interarrivals broadcast to every ingress, plus the
    price/carbon signal timelines the price-spike overlay needs."""
    import numpy as np

    rng = np.random.default_rng(seed)
    times = np.round(np.cumsum(rng.exponential(1.0 / rate, n_events)), 6)
    bins = 24
    price = np.round(0.08 + 0.04 * np.sin(
        np.linspace(0.0, 2.0 * np.pi, bins, endpoint=False)), 6)
    return {
        "name": "twin_probe",
        "streams": {"inference": {"kind": "trace",
                                  "times": times.tolist()},
                    "training": {"kind": "off"}},
        "signals": {"price": price.tolist(), "carbon": [420.0, 310.0],
                    "bin_s": 300.0, "periodic": True},
    }


def twin_latency_probe(horizon_s=300.0, chunk_steps=512, reps=9,
                       warm_chunks=8):
    """Round-19 twin serving SLO: fork+forecast wall latency off a warm
    resident twin, p50/p95 over interleaved repeated queries.

    A duo-fleet twin ingests a deterministic 4096-event trace (open
    cursor — the serving-mode shape) and warms up a bounded number of
    chunks; then the SAME forecast query — 2 policies x 2 overlays
    (price spike + regional blackout) vmapped into buckets off the warm
    state — runs ``reps`` times.  The warm rep doubles as the
    correctness gate: the warm state is bit-unchanged by the fork (fork
    purity) and a repeated query returns byte-identical JSON
    (determinism — also proof the overlay/fault/runner caches hold, the
    mechanism that makes the SLO achievable at all).  ev_s is forecast
    events/sec at the p50 — the higher-is-better number
    analysis/ledger.py trends as the ``twin_latency`` record kind.
    Banked as ``bench_results/twin_r19.json`` (``python bench.py
    --twin``); scripts/summarize_bench.py renders the quantiles and
    ``scripts/perf_ledger.py --check`` gates them.
    """
    import jax
    import numpy as np

    from distributed_cluster_gpus_tpu.configs import build_duo_fleet
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.twin import (
        Overlay, Twin, TraceCursor, forecast)

    fleet = build_duo_fleet()
    cursor = TraceCursor(fleet, _twin_probe_base_doc())
    params = SimParams(algo="default_policy", duration=600.0, seed=0)
    twin = Twin(fleet, params, cursor, chunk_steps=chunk_steps)
    adv = twin.advance(max_chunks=warm_chunks)
    assert twin.chunk > 0, "twin probe: no chunk accepted during warm-up"

    policies = ("default_policy", "eco_route")
    overlays = (Overlay(kind="price_spike"), Overlay(kind="blackout"))
    query = lambda: forecast(  # noqa: E731
        twin, policies, overlays, horizon_s, chunk_steps=chunk_steps)

    def snap(st):
        # typed PRNG-key leaves refuse np.asarray: unwrap to key data
        return [np.asarray(
                    jax.random.key_data(x)
                    if jax.dtypes.issubdtype(getattr(x, "dtype", np.float32),
                                             jax.dtypes.prng_key) else x
                ).tolist() for x in jax.tree.leaves(jax.device_get(st))]
    s0 = snap(twin.state)
    r1 = query()
    assert snap(twin.state) == s0, \
        "twin probe: forecast mutated the warm state (fork purity)"
    r2 = query()
    j1 = json.dumps(r1, sort_keys=True, default=float)
    assert j1 == json.dumps(r2, sort_keys=True, default=float), \
        "twin probe: repeated forecast is not byte-identical"

    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res = query()
        walls.append(time.perf_counter() - t0)
    p50 = sorted(walls)[reps // 2]
    p95 = sorted(walls)[min(reps - 1, int(0.95 * reps))]
    events = int(res["events_forecast"])
    n_lanes = len(res["lanes"])
    sys.stderr.write(
        f"[bench] twin latency: {n_lanes} lanes in "
        f"{len(res['buckets'])} buckets off t0={res['t0']:.1f}s — "
        f"p50 {p50 * 1e3:.1f} ms, p95 {p95 * 1e3:.1f} ms, "
        f"{events / p50:,.0f} forecast ev/s\n")
    return {
        "note": ("round-19 twin fork+forecast SLO: warm duo-fleet twin, "
                 "2 policies x 2 overlays vmapped off the live state, "
                 "interleaved repeated queries (warm rep asserts fork "
                 "purity + byte-identical determinism); ev_s is "
                 "forecast events/sec at the p50 wall"),
        "fleet": "duo", "n_lanes": n_lanes,
        "n_buckets": len(res["buckets"]), "buckets": res["buckets"],
        "policies": list(policies),
        "overlays": [ov.name for ov in overlays],
        "horizon_s": horizon_s, "chunk_steps": chunk_steps,
        "reps": reps, "warm_chunks": int(adv["chunks"]),
        "t0_s": round(res["t0"], 3),
        "events_forecast": events,
        "p50_s": round(p50, 4), "p95_s": round(p95, 4),
        "ev_s": round(events / p50, 1),
    }


def main():
    # defaults = the best-known config from the round-2 TPU sweep
    # (bench_results/sweep_r02_preopt.json: R=256/J=128 beats J=256 2x)
    n_rollouts = int(os.environ.get("BENCH_ROLLOUTS", 256))
    chunk_steps = int(os.environ.get("BENCH_CHUNK", 512))
    n_chunks = int(os.environ.get("BENCH_CHUNKS", 8))
    job_cap = int(os.environ.get("BENCH_JOB_CAP", 128))
    sweep = os.environ.get("BENCH_SWEEP", "") not in ("", "0")
    profile_dir = os.environ.get("BENCH_PROFILE") or None
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
    probe_retries = int(os.environ.get("BENCH_PROBE_RETRIES", 2))

    note = None
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        import jax

        jax.config.update("jax_platforms", "cpu")  # axon overrides the env var
        platform = "cpu"
    else:
        n_dev, platform = probe_tpu(probe_timeout, probe_retries)
        if platform is None or platform not in ("tpu", "axon"):
            # persistent backend failure: degrade to a LABELED cpu fallback
            note = "tpu backend unavailable (probe failed); CPU fallback result"
            sys.stderr.write(f"[bench] {note}\n")
            import jax

            jax.config.update("jax_platforms", "cpu")
            platform = "cpu"

    import jax

    # persistent XLA compilation cache: the bench recompiles identical
    # multi-minute programs on every invocation (driver round-end runs,
    # recovery-suite stages, fallback + cost-model AOT compiles) — cache
    # them across processes.  Repo-local dir, gitignored; harmless if the
    # backend ignores it.
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(HERE, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
        # bound the cache (LRU-evicted past this): a sweep compiles ~9
        # multi-minute programs and source changes orphan old entries
        jax.config.update("jax_compilation_cache_max_size", 2 * 1024**3)
    except Exception as e:  # noqa: BLE001 - cache is an optimization only
        sys.stderr.write(f"[bench] compilation cache unavailable: {e!r}\n")

    n_dev = len(jax.devices())

    if note is not None and "BENCH_ROLLOUTS" not in os.environ:
        # CPU fallback: the TPU-sized default rollout batch only slows the
        # single-core measurement down; shrink it (config is in the JSON)
        n_rollouts = min(n_rollouts, 32)

    configs = [(n_rollouts, job_cap)]
    if sweep:
        # J=512 included per the round-2 verdict: the north-star claim must
        # hold at paper-world job backlogs, not only the fast J=128 corner
        configs = [(r, j) for r in (128, 256, 512) for j in (128, 256, 512)]
    elif platform != "cpu" and "BENCH_JOB_CAP" not in os.environ:
        # on-chip default run: also measure the paper-backlog slab so the
        # recorded JSON carries the J=512 number the north star requires
        # (the CPU fallback skips it — the big slab is prohibitively slow
        # on one core and the fallback is only a liveness signal)
        configs = [(n_rollouts, job_cap), (n_rollouts, 512)]

    # profile the user's configured shape: the last sweep config when
    # sweeping (legacy behavior), else the FIRST config — the on-chip
    # J=512 extra appended below must not hijack the trace
    profile_at = len(configs) - 1 if sweep else 0

    with_cost = os.environ.get("BENCH_COST", "1") not in ("", "0")

    results = []
    cm = None
    for i, (r, j) in enumerate(configs):
        try:
            rate, events, wall, cm_i = measure(
                r, chunk_steps, n_chunks, j,
                profile_dir=profile_dir if i == profile_at else None,
                with_cost=with_cost and i == profile_at, platform=platform)
            cm = cm_i or cm
            results.append({"rollouts": r, "job_cap": j,
                            "events_per_sec": round(rate, 1),
                            "events": events, "wall_s": round(wall, 2)})
            sys.stderr.write(f"[bench] R={r} J={j}: {rate:,.0f} ev/s\n")
        except Exception as e:  # keep sweeping; report what worked
            sys.stderr.write(f"[bench] R={r} J={j} failed: {e!r}\n")

    if not results:
        print(json.dumps({
            "metric": "sim_job_steps_per_sec_rl_in_loop",
            "value": 0.0, "unit": "events/sec", "vs_baseline": 0.0,
            "error": "all bench configurations failed; see stderr",
        }))
        return

    best = max(results, key=lambda x: x["events_per_sec"])
    if with_cost and cm is None:
        # the profile_at config failed (its measure() raised): don't lose
        # the round's cost-model evidence — compile-only on the best
        # measured shape instead
        try:
            cm = cost_model_compile_only(best["rollouts"], chunk_steps,
                                         best["job_cap"], platform)
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"[bench] fallback cost model failed: {e!r}\n")
    target = 1e6 * (n_dev / 8.0 if platform != "cpu" else 1.0)
    out = {
        "metric": "sim_job_steps_per_sec_rl_in_loop",
        "value": best["events_per_sec"],
        "unit": "events/sec",
        "vs_baseline": round(best["events_per_sec"] / target, 4),
        "platform": platform, "n_devices": n_dev,
        # superstep_k of the headline pipeline: chsac_af is statically
        # superstep-ineligible, so the RL bench always runs singleton;
        # the coalescing lever is measured by the superstep sweep below
        "config": {"rollouts": best["rollouts"], "job_cap": best["job_cap"],
                   "chunk_steps": chunk_steps, "chunks": n_chunks,
                   "superstep_k": 1},
    }
    if os.environ.get("BENCH_SUPERSTEP", "1") not in ("", "0"):
        # K in {1,2,4,8} engine sweep at the bench shape (R=32, J=128):
        # banks the measured coalescing throughput + the per-event eqn
        # counts next to the headline number (BENCH_SUPERSTEP=0 skips)
        try:
            out["superstep_sweep"] = superstep_sweep()
        except Exception as e:  # noqa: BLE001 - sweep must not kill the bench
            sys.stderr.write(f"[bench] superstep sweep failed: {e!r}\n")
        # host/device overlap of the pipelined run_simulation drain
        # (round 7): banked next to the sweep so the round's JSON carries
        # both halves of the perf story
        try:
            out["io_overlap"] = io_overlap_probe()
        except Exception as e:  # noqa: BLE001 - probe must not kill the bench
            sys.stderr.write(f"[bench] io overlap probe failed: {e!r}\n")
        # telemetry cost at the canonical K (round 8): ev/s with the obs
        # subsystem compiled off vs on, banked next to the sweep so the
        # <= 5% acceptance gate has a measured number (BENCH_OBS=0 skips)
        if os.environ.get("BENCH_OBS", "1") not in ("", "0"):
            try:
                out["obs_overhead"] = obs_overhead_probe()
            except Exception as e:  # noqa: BLE001 - probe must not kill the bench
                sys.stderr.write(f"[bench] obs overhead probe failed: {e!r}\n")
    if os.environ.get("BENCH_WORKLOAD", "1") not in ("", "0"):
        # trace-replay workload throughput (round 10): the flash-crowd
        # preset with live price/carbon signals, ev/s + step-body census
        # (while MUST be 0 — the workload compiler's contract);
        # BENCH_WORKLOAD=0 skips
        try:
            out["workload_probe"] = workload_probe()
        except Exception as e:  # noqa: BLE001 - probe must not kill the bench
            sys.stderr.write(f"[bench] workload probe failed: {e!r}\n")
    if os.environ.get("BENCH_CENSUS", "1") not in ("", "0"):
        # per-class jaxpr op census (round 9): trace-only (no compile),
        # banked so op-count regressions across rounds diff by KIND
        # (scatter/select/while...) instead of one opaque eqn total
        try:
            out["op_census"] = _load_count_step_ops().census_matrix()
        except Exception as e:  # noqa: BLE001 - census must not kill the bench
            sys.stderr.write(f"[bench] op census failed: {e!r}\n")
    if os.environ.get("BENCH_LINT", "1") not in ("", "0"):
        # dcg-lint rule matrix (round 13): trace-only (no compile), so
        # the structural-invariant pass/fail per canonical config rides
        # every banked round (dcg.lint_report.v1, docs/static_analysis
        # .md) for the cost of ~23 traces.  x64=False here: the second
        # enable_x64 trace per config doubles that cost and the
        # weak-type rule is already enforced by the lint CLI and the
        # quick tier — the banked artifact carries the structural
        # rules.  BENCH_LINT=0 skips entirely.
        try:
            from distributed_cluster_gpus_tpu.analysis import lint as _lint

            out["lint_report"] = _lint.run_lint(x64=False)
        except Exception as e:  # noqa: BLE001 - lint must not kill the bench
            sys.stderr.write(f"[bench] graph lint failed: {e!r}\n")
    if os.environ.get("BENCH_ATTRIB", "1") not in ("", "0"):
        # step-time attribution (round 14): the canonical joint_nf K=1 +
        # K=4 phase partitions with measured per-phase ms/step, banked so
        # every round records WHERE inside the step the wall time went
        # (analysis/attrib.py; ~7 small extra compiles per config).
        # BENCH_ATTRIB=0 skips for constrained environments.
        try:
            from distributed_cluster_gpus_tpu.analysis import attrib
            from distributed_cluster_gpus_tpu.configs import build_fleet

            fleet = build_fleet()
            out["phase_attrib"] = [
                attrib.attribute_config(fleet, name, n_rollouts=8,
                                        chunk_steps=256, reps=3)
                for name in ("joint_nf/ring/K1", "joint_nf/ring/K4")]
            for rep in out["phase_attrib"]:
                top = rep.get("top_phase") or {}
                sys.stderr.write(
                    f"[bench] phase attrib {rep['config']}: top phase "
                    f"{top.get('phase')} at {top.get('time_share', 0) or 0:.0%} "
                    f"of {rep['measured']['whole_step_ms']:.3f} ms/step\n")
        except Exception as e:  # noqa: BLE001 - attrib must not kill the bench
            sys.stderr.write(f"[bench] phase attribution failed: {e!r}\n")
    if os.environ.get("BENCH_TWIN", "1") not in ("", "0"):
        # twin serving SLO (round 19): fork+forecast latency quantiles
        # off a warm resident twin (twin/), banked before the ledger
        # block so the twin_latency record rides the same gate pass.
        # BENCH_TWIN=0 skips.
        try:
            out["twin_latency"] = twin_latency_probe()
        except Exception as e:  # noqa: BLE001 - probe must not kill the bench
            sys.stderr.write(f"[bench] twin latency probe failed: {e!r}\n")
    if os.environ.get("BENCH_LEDGER", "1") not in ("", "0"):
        # continuous perf ledger (round 14): refresh bench_results/
        # ledger.jsonl from every banked round (idempotent) and gate the
        # just-measured headline against the banked best — the check
        # result is banked as evidence (the enforcing nonzero-exit gate
        # is scripts/perf_ledger.py --check).  BENCH_LEDGER=0 skips.
        try:
            from distributed_cluster_gpus_tpu.analysis import ledger

            ing = ledger.ingest(HERE)
            current = ledger.records_from("<current>", dict(out))
            regressions = ledger.check(
                ledger.read_ledger(ledger.ledger_path(HERE)), current,
                threshold=float(os.environ.get("BENCH_LEDGER_THRESHOLD",
                                               0.3)))
            out["perf_ledger"] = {
                "ingested": ing["added"], "total": ing["total"],
                "skipped": [list(s) for s in ing["skipped"]],
                "regressions": regressions,
            }
            if regressions:
                for r in regressions:
                    sys.stderr.write(
                        f"[bench] LEDGER REGRESSION {r['config']}: "
                        f"{r['current_ev_s']:,.0f} ev/s vs banked best "
                        f"{r['best_ev_s']:,.0f} ({r['best_source']}, "
                        f"-{r['drop_fraction'] * 100:.0f}%)\n")
        except Exception as e:  # noqa: BLE001 - ledger must not kill the bench
            sys.stderr.write(f"[bench] perf ledger failed: {e!r}\n")
    if cm:
        out["cost_model"] = cm
    if with_cost and note is not None:
        # wedged-tunnel round: bank the north-star-shape projection next to
        # the shrunken CPU liveness number (VERDICT r04 item 1)
        try:
            ns = cost_model_compile_only(256, chunk_steps, 512, platform)
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"[bench] north-star cost model failed: {e!r}\n")
            ns = None
        if ns:
            out["cost_model_north_star"] = ns
    if sweep:
        out["sweep"] = results
    elif len(results) > 1:
        # every measured config lands in the record (the J=512 on-chip
        # extra exists precisely to be recorded, not just printed best-of)
        out["configs_measured"] = results
    if note:
        out["note"] = note
        prior = best_prior_on_chip()
        if prior:
            # the tunnel can be up for a midday window (captured by
            # scripts/tpu_watcher.sh) and wedged again at round end: a CPU
            # fallback must not hide on-chip evidence that already exists
            out["best_on_chip_prior"] = prior
    print(json.dumps(out))


def fastpath_main():
    """`python bench.py --fastpath [out.json]`: run ONLY the round-12
    fast-path A/B probe and bank it (default
    bench_results/fastpath_r12.json).  Separate entry: the probe pays
    ~10 XLA compiles and needs no TPU probe/backoff machinery — it is
    meaningful on any platform, like the superstep sweep."""
    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(HERE, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          5.0)
        jax.config.update("jax_compilation_cache_max_size", 2 * 1024**3)
    except Exception as e:  # noqa: BLE001 - cache is an optimization only
        sys.stderr.write(f"[bench] compilation cache unavailable: {e!r}\n")
    args = [a for a in sys.argv[2:] if not a.startswith("-")]
    out_path = args[0] if args else os.path.join(
        HERE, "bench_results", "fastpath_r12.json")
    probe = fastpath_ab_probe(
        chunk_steps=int(os.environ.get("BENCH_CHUNK", 512)),
        n_rollouts=int(os.environ.get("BENCH_ROLLOUTS", 32)),
        job_cap=int(os.environ.get("BENCH_JOB_CAP", 128)),
        reps=int(os.environ.get("BENCH_REPS", 3)))
    out = {"fastpath_ab": probe,
           "platform": jax.devices()[0].platform,
           "note": probe["note"]}
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({"wrote": out_path,
                      "rows": [(r["config"], r["mode"], r["k"],
                                r["speedup"]) for r in probe["rows"]]}))


def sweep_grid_main():
    """`python bench.py --sweep-grid [out.json]`: run ONLY the round-16
    sweep-grid A/B probe and bank it (default
    bench_results/sweep_r16.json).  Separate entry like --fastpath: the
    probe needs no TPU probe/backoff machinery and is meaningful on any
    platform — on CPU it is the dispatch-amortization headline."""
    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(HERE, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          5.0)
        jax.config.update("jax_compilation_cache_max_size", 2 * 1024**3)
    except Exception as e:  # noqa: BLE001 - cache is an optimization only
        sys.stderr.write(f"[bench] compilation cache unavailable: {e!r}\n")
    args = [a for a in sys.argv[2:] if not a.startswith("-")]
    out_path = args[0] if args else os.path.join(
        HERE, "bench_results", "sweep_r16.json")
    probe = sweep_grid_probe(
        duration=float(os.environ.get("BENCH_SWEEP_DURATION", 120.0)),
        chunk_steps=int(os.environ.get("BENCH_CHUNK", 512)),
        reps=int(os.environ.get("BENCH_REPS", 3)))
    out = {"sweep_grid_probe": probe,
           "platform": jax.devices()[0].platform,
           "note": probe["note"]}
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({"wrote": out_path,
                      "grid_cells_s": probe["grid_cells_s"],
                      "serial_cells_s": probe["serial_cells_s"],
                      "speedup_cells": probe["speedup_cells"]}))


def twin_main():
    """`python bench.py --twin [out.json]`: run ONLY the round-19 twin
    fork+forecast latency probe and bank it (default
    bench_results/twin_r19.json).  Separate entry like --sweep-grid:
    no TPU probe/backoff machinery, meaningful on any platform."""
    import jax

    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(HERE, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          5.0)
        jax.config.update("jax_compilation_cache_max_size", 2 * 1024**3)
    except Exception as e:  # noqa: BLE001 - cache is an optimization only
        sys.stderr.write(f"[bench] compilation cache unavailable: {e!r}\n")
    args = [a for a in sys.argv[2:] if not a.startswith("-")]
    out_path = args[0] if args else os.path.join(
        HERE, "bench_results", "twin_r19.json")
    probe = twin_latency_probe(
        horizon_s=float(os.environ.get("BENCH_TWIN_HORIZON", 300.0)),
        chunk_steps=int(os.environ.get("BENCH_CHUNK", 512)),
        reps=int(os.environ.get("BENCH_REPS", 9)))
    out = {"twin_latency": probe,
           "platform": jax.devices()[0].platform,
           "note": probe["note"]}
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({"wrote": out_path,
                      "p50_s": probe["p50_s"], "p95_s": probe["p95_s"],
                      "ev_s": probe["ev_s"]}))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--fastpath":
        fastpath_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--sweep-grid":
        sweep_grid_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--twin":
        twin_main()
    else:
        main()

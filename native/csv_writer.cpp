// Native CSV emission drain for the simulator's two log schemas.
//
// The host-side drain is the one serial bottleneck of long runs: a 7-day
// multi-DC simulation emits millions of formatted rows, and Python's csv
// module burns ~µs-per-field.  This writer produces byte-identical output
// to sim/io.py's Python fallback (same printf formats) at fwrite speed.
//
// Interface (ctypes, C ABI): rows arrive as packed float32 exactly as the
// engine emits them (see engine.CLUSTER_COLS / JOB_COLS); entity names are
// passed once as a '\n'-joined blob and indexed per row.
//
// Build: make -C native   (g++ -O2 -shared -fPIC)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

std::vector<std::string> split_names(const char* blob) {
  std::vector<std::string> out;
  const char* p = blob;
  while (p && *p) {
    const char* nl = strchr(p, '\n');
    if (!nl) {
      out.emplace_back(p);
      break;
    }
    out.emplace_back(p, nl - p);
    p = nl + 1;
  }
  return out;
}

}  // namespace

extern "C" {

// rows: [n_ticks, n_dc, 14] float32 in engine CLUSTER_COLS order.
// Returns number of data rows written, or -1 on I/O error.
int64_t write_cluster_rows(const char* path, const float* rows,
                           int64_t n_ticks, int64_t n_dc,
                           const char* dc_names_blob) {
  FILE* f = fopen(path, "a");
  if (!f) return -1;
  auto names = split_names(dc_names_blob);
  int64_t written = 0;
  for (int64_t t = 0; t < n_ticks; ++t) {
    for (int64_t d = 0; d < n_dc; ++d) {
      const float* c = rows + (t * n_dc + d) * 14;
      // time_s,dc,freq,busy,free,run_total,run_inf,run_train,q_inf,q_train,
      // util_inst,util_avg,acc_job_unit,power_W,energy_kJ
      fprintf(f, "%.3f,%s,%.2f,%d,%d,%d,%d,%d,%d,%d,%.4f,%.4f,%.4f,%.2f,%.4f\r\n",
              c[0], names[d].c_str(), c[1], (int)c[2], (int)c[3], (int)c[4],
              (int)c[5], (int)c[6], (int)c[7], (int)c[8], c[9], c[10], c[11],
              c[12], c[13]);
      ++written;
    }
  }
  fclose(f);
  return written;
}

// rows: [n, 15] float32 in engine JOB_COLS order.
int64_t write_job_rows(const char* path, const float* rows, int64_t n,
                       const char* ingress_names_blob,
                       const char* dc_names_blob) {
  FILE* f = fopen(path, "a");
  if (!f) return -1;
  auto ing = split_names(ingress_names_blob);
  auto dcs = split_names(dc_names_blob);
  for (int64_t i = 0; i < n; ++i) {
    const float* c = rows + i * 15;
    const char* jtype = ((int)c[2] == 0) ? "inference" : "training";
    // jid,ingress,type,size,dc,f_used,n_gpus,net_lat_s,start_s,finish_s,
    // latency_s,preempt_count,T_pred,P_pred,E_pred
    fprintf(f, "%d,%s,%s,%.4f,%s,%.3f,%d,%.4f,%.6f,%.6f,%.6f,%d,%.6f,%.2f,%.2f\r\n",
            (int)c[0], ing[(int)c[1]].c_str(), jtype, c[3],
            dcs[(int)c[4]].c_str(), c[5], (int)c[6], c[7], c[8], c[9], c[10],
            (int)c[11], c[12], c[13], c[14]);
  }
  fclose(f);
  return n;
}

}  // extern "C"

#!/usr/bin/env bash
# 1-DC/1-ingress debug topology runs (counterpart of single_dc_debug.bat):
# pins (n, f) via the debug algo so closed-form T/P/E can be hand-checked
# against the logs — the reference's own verification methodology
# (SURVEY.md §4).
set -euo pipefail

OUT_ROOT="${OUT_ROOT:-runs_single_dc}"
DURATION="${DURATION:-600}"

for nf in "1 1.0" "4 1.0" "8 0.6"; do
    set -- $nf
    n="$1"; f="$2"
    out="$OUT_ROOT/debug_n${n}_f${f}"
    echo "=== debug n=$n f=$f -> $out"
    python run_sim.py --algo debug --single-dc --duration "$DURATION" \
        --log-interval 5 --inf-mode poisson --inf-rate 2.0 --trn-mode off \
        --num_fixed_gpus "$n" --fixed_freq "$f" --out "$out" --quiet
done

python run_sim.py --algo default_policy --single-dc --duration "$DURATION" \
    --log-interval 5 --inf-mode poisson --inf-rate 2.0 --trn-mode poisson \
    --trn-rate 0.05 --out "$OUT_ROOT/default_policy" --quiet

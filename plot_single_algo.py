"""Per-run / per-DC debug plots from one run's cluster_log.csv + job_log.csv.

Capability parity with `/root/reference/plot_single_algo.py:12-268`: 8 figure
families for a single run —

  per-DC queue lengths, per-DC utilization, per-DC busy GPUs, per-DC
  cumulative energy, frequency & n-GPU trend over time (rolling mean),
  job-count distribution per DC, jobs per ingress, and the ingress -> DC
  routing heatmap.

Usage:
    python plot_single_algo.py --run runs/chsac --outdir figs_chsac [--pdf]
"""

import argparse
import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

try:
    import seaborn as sns

    sns.set_theme(style="whitegrid")
    HAS_SNS = True
except Exception:  # pragma: no cover
    HAS_SNS = False


def _save(fig, outdir, name, pdf=False):
    path = os.path.join(outdir, f"{name}.{'pdf' if pdf else 'png'}")
    fig.savefig(path, dpi=130, bbox_inches="tight")
    plt.close(fig)
    print(f"wrote {path}")


def per_dc_lines(cl, col, title, ylabel, outdir, name, pdf, cumulative=False):
    fig, ax = plt.subplots(figsize=(9, 4.5))
    for dc, grp in cl.groupby("dc"):
        y = grp[col].to_numpy()
        ax.plot(grp["time_s"], y, label=dc, lw=1.0)
    ax.set_xlabel("time (s)")
    ax.set_ylabel(ylabel)
    ax.set_title(title)
    ax.legend(fontsize=8, ncols=2)
    _save(fig, outdir, name, pdf)


def fig_queues_per_dc(cl, outdir, pdf):
    fig, axes = plt.subplots(2, 1, figsize=(9, 7), sharex=True)
    for dc, grp in cl.groupby("dc"):
        axes[0].plot(grp["time_s"], grp["q_inf"], label=dc, lw=1.0)
        axes[1].plot(grp["time_s"], grp["q_train"], label=dc, lw=1.0)
    axes[0].set_ylabel("inference queue")
    axes[1].set_ylabel("training queue")
    axes[1].set_xlabel("time (s)")
    axes[0].set_title("per-DC queue lengths")
    axes[0].legend(fontsize=8, ncols=2)
    _save(fig, outdir, "per_dc_queues", pdf)


def fig_fn_trend(jb, outdir, pdf, window=50):
    """Rolling mean of chosen frequency and GPU count over start time."""
    if not len(jb):
        return
    jb = jb.sort_values("start_s")
    fig, axes = plt.subplots(2, 1, figsize=(9, 6), sharex=True)
    for jtype, color in (("inference", "tab:blue"), ("training", "tab:orange")):
        sel = jb[jb["type"] == jtype]
        if len(sel) < 5:
            continue
        roll_f = sel["f_used"].rolling(window, min_periods=5).mean()
        roll_n = sel["n_gpus"].rolling(window, min_periods=5).mean()
        axes[0].plot(sel["start_s"], roll_f, label=jtype, color=color, lw=1.2)
        axes[1].plot(sel["start_s"], roll_n, label=jtype, color=color, lw=1.2)
    axes[0].set_ylabel("frequency (rolling mean)")
    axes[1].set_ylabel("n GPUs (rolling mean)")
    axes[1].set_xlabel("job start time (s)")
    axes[0].set_title(f"(f, n) decision trend (window {window})")
    axes[0].legend()
    _save(fig, outdir, "freq_ngpu_trend", pdf)


def fig_job_distribution(jb, outdir, pdf):
    fig, ax = plt.subplots(figsize=(8, 4))
    counts = jb.groupby(["dc", "type"]).size().unstack(fill_value=0)
    counts.plot.bar(ax=ax)
    ax.set_ylabel("jobs")
    ax.set_title("jobs per DC by type")
    plt.xticks(rotation=30, ha="right")
    _save(fig, outdir, "jobs_per_dc", pdf)


def fig_jobs_per_ingress(jb, outdir, pdf):
    fig, ax = plt.subplots(figsize=(8, 4))
    counts = jb.groupby(["ingress", "type"]).size().unstack(fill_value=0)
    counts.plot.bar(ax=ax)
    ax.set_ylabel("jobs")
    ax.set_title("jobs per ingress by type")
    plt.xticks(rotation=30, ha="right")
    _save(fig, outdir, "jobs_per_ingress", pdf)


def fig_routing_heatmap(jb, outdir, pdf):
    """ingress -> DC job-count matrix (reference `:197-227`)."""
    mat = jb.groupby(["ingress", "dc"]).size().unstack(fill_value=0)
    fig, ax = plt.subplots(figsize=(8, 6))
    if HAS_SNS:
        sns.heatmap(mat, annot=True, fmt="d", cmap="viridis", ax=ax,
                    cbar_kws={"label": "jobs routed"})
    else:
        im = ax.imshow(mat.to_numpy(), cmap="viridis")
        ax.set_xticks(range(len(mat.columns)), mat.columns, rotation=45)
        ax.set_yticks(range(len(mat.index)), mat.index)
        fig.colorbar(im, ax=ax)
    ax.set_title("routing: ingress -> DC")
    _save(fig, outdir, "routing_heatmap", pdf)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--run", required=True, help="run directory with the two CSVs")
    ap.add_argument("--outdir", default=None)
    ap.add_argument("--pdf", action="store_true")
    ap.add_argument("--rolling", type=int, default=50)
    a = ap.parse_args(argv)
    outdir = a.outdir or os.path.join(a.run, "figs")
    os.makedirs(outdir, exist_ok=True)

    cl = pd.read_csv(os.path.join(a.run, "cluster_log.csv"))
    jb = pd.read_csv(os.path.join(a.run, "job_log.csv"))

    fig_queues_per_dc(cl, outdir, a.pdf)
    per_dc_lines(cl, "util_inst", "per-DC instantaneous utilization",
                 "fraction busy", outdir, "per_dc_utilization", a.pdf)
    per_dc_lines(cl, "busy", "per-DC busy GPUs", "GPUs", outdir,
                 "per_dc_busy", a.pdf)
    per_dc_lines(cl, "energy_kJ", "per-DC cumulative energy", "kJ", outdir,
                 "per_dc_energy", a.pdf)
    fig_fn_trend(jb, outdir, a.pdf, a.rolling)
    fig_job_distribution(jb, outdir, a.pdf)
    fig_jobs_per_ingress(jb, outdir, a.pdf)
    fig_routing_heatmap(jb, outdir, a.pdf)


if __name__ == "__main__":
    main()

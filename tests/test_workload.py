"""workload/ subsystem: spec compilation, byte-identity, continuity, signals.

The contracts pinned here (docs/workloads.md):

* legacy synthetic configs routed through the workload compiler as an
  EXPLICIT spec byte-compare against the plain SimParams-field path —
  the compiler is the one arrival code path, not a parallel
  reimplementation;
* pregenerated tables are chunk-invariant: a run split into chunks is
  bit-identical to the single-chunk run (the retired round-6..9
  "re-anchoring" caveat; the superstep-K side lives in
  tests/test_superstep.py::test_chunk_boundary_continuity_exact);
* trace replay fires arrivals at exactly the replayed timestamps with
  the replayed sizes, and exhausted traces go silent;
* rate timelines realize their piecewise rates (flash-crowd windows
  spike, constant timelines match Poisson);
* signal timelines: price/carbon columns in cluster_log, cost/carbon
  accruals in the state and evaluation summary, legacy-equivalent
  timelines reproduce the static-table results;
* scripts/validate_workload.py accepts the documented schema and
  rejects malformed specs (negative cases);
* the week-horizon J=8192 acceptance run completes as ONE scan.
"""

import dataclasses
import filecmp
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
from distributed_cluster_gpus_tpu.sim.io import run_simulation
from distributed_cluster_gpus_tpu.workload import (
    SignalSpec,
    StreamSpec,
    WorkloadSpec,
    load_workload_json,
    make_preset,
)


def _fresh(st):
    return jax.tree.map(jnp.copy, st)


from conftest import tree_mismatches as _mismatches


BASE_KW = dict(duration=60.0, log_interval=5.0, inf_mode="sinusoid",
               inf_rate=2.0, inf_amp=0.6, inf_period=300.0,
               trn_mode="poisson", trn_rate=0.1, job_cap=128,
               lat_window=256, seed=3, queue_cap=256)


def _legacy_equiv_spec():
    """The explicit WorkloadSpec equal to BASE_KW's synthetic fields."""
    return WorkloadSpec(streams=(
        StreamSpec(kind="sinusoid", rate=2.0, amp=0.6, period=300.0),
        StreamSpec(kind="poisson", rate=0.1)), name="legacy_equiv")


def test_legacy_spec_byte_identical(fleet, tmp_path):
    """HEAD-golden satellite: the legacy synthetic config expressed as an
    explicit WorkloadSpec byte-compares against the SimParams-field path
    (which itself routes through the compiler via `legacy_spec`) — same
    CSVs, same final state.  eco_route exercises size-dependent routing,
    so a single drifted draw diverges the whole log."""
    base = SimParams(algo="eco_route", **BASE_KW)
    spec = dataclasses.replace(base, workload=_legacy_equiv_spec())
    outs = {}
    for name, params in (("fields", base), ("spec", spec)):
        outs[name] = str(tmp_path / name)
        run_simulation(fleet, params, out_dir=outs[name], chunk_steps=512)
    for name in ("cluster_log.csv", "job_log.csv"):
        assert filecmp.cmp(f"{outs['fields']}/{name}",
                           f"{outs['spec']}/{name}", shallow=False), (
            f"{name}: spec-routed workload diverged from the legacy "
            "params-field path")


def test_multichunk_cursor_continuity(fleet):
    """A chunked run bit-equals the single-chunk run (pregen on, the
    default sinusoid inversion + poisson fold): the cursor and fold
    carries compose exactly across chunk boundaries."""
    params = SimParams(algo="default_policy", **BASE_KW)
    st0 = init_state(jax.random.key(params.seed), fleet, params)
    eng = Engine(fleet, params)
    one, _ = eng.run_chunk(_fresh(st0), None, n_steps=8192)
    many = _fresh(st0)
    for _ in range(16):
        many, _ = eng.run_chunk(many, None, n_steps=512)
    bad = [p for p in _mismatches(one, many) if p != ".key"]
    assert not bad, f"chunking moved state leaves: {bad}"
    assert int(one.n_events) > 1000  # not vacuous


def test_trace_replay_exact(fleet, tmp_path):
    """A trace stream fires arrivals at exactly the replayed timestamps
    with the replayed sizes — and goes silent once exhausted."""
    times = np.asarray([1.0, 2.5, 4.0, 4.0, 9.75, 30.0])
    sizes = np.asarray([5.0, 3.0, 2.0, 8.0, 1.5, 2.5])
    spec = WorkloadSpec(streams=(
        StreamSpec(kind="trace", times=times, sizes=sizes),
        StreamSpec(kind="off")), name="replay")
    params = SimParams(algo="joint_nf", **dict(BASE_KW, workload=spec))
    out = str(tmp_path / "trace")
    st = run_simulation(fleet, params, out_dir=out, chunk_steps=256)
    # every trace arrival fired exactly once (jid_counter counts from 1),
    # then the stream went silent: no drops, no extra arrivals
    assert int(st.jid_counter) - 1 == len(times) * fleet.n_ing
    assert int(st.n_dropped) == 0
    assert bool(np.all(np.isinf(np.asarray(st.next_arrival))))
    rows = open(os.path.join(out, "job_log.csv")).read().splitlines()[1:]
    got = sorted(float(r.split(",")[3]) for r in rows)
    want = sorted(float(s) for s in sizes) * fleet.n_ing
    np.testing.assert_allclose(got, sorted(want), rtol=1e-4)


def test_trace_multichunk_continuity(fleet):
    """Trace replay is chunk-invariant like every other stream kind."""
    times = np.cumsum(np.full(200, 0.25))
    spec = WorkloadSpec(streams=(
        StreamSpec(kind="trace", times=times),
        StreamSpec(kind="poisson", rate=0.1)), name="replay_mc")
    params = SimParams(algo="default_policy", **dict(BASE_KW, workload=spec))
    st0 = init_state(jax.random.key(0), fleet, params)
    eng = Engine(fleet, params)
    one, _ = eng.run_chunk(_fresh(st0), None, n_steps=8192)
    many = _fresh(st0)
    for _ in range(8):
        many, _ = eng.run_chunk(many, None, n_steps=1024)
    bad = [p for p in _mismatches(one, many) if p != ".key"]
    assert not bad, bad


def test_rate_timeline_constant_matches_poisson_stats(fleet):
    """A constant rate timeline is a Poisson process: arrival totals over
    a horizon agree with the poisson kind at ~1/sqrt(n) tolerance."""
    kw = dict(BASE_KW, duration=120.0)
    specs = {
        "tl": WorkloadSpec(streams=(
            StreamSpec(kind="rate_timeline", rates=np.full(4, 2.0),
                       bin_s=30.0, periodic=True),
            StreamSpec(kind="off")), name="tl"),
        "po": WorkloadSpec(streams=(
            StreamSpec(kind="poisson", rate=2.0),
            StreamSpec(kind="off")), name="po"),
    }
    counts = {}
    for name, spec in specs.items():
        params = SimParams(algo="default_policy", **dict(kw, workload=spec))
        st = run_simulation(fleet, params, out_dir=None, chunk_steps=4096)
        counts[name] = int(st.jid_counter) - 1
    assert counts["po"] > 500
    assert abs(counts["tl"] - counts["po"]) / counts["po"] < 0.1, counts


def test_flash_crowd_rate_spike(fleet):
    """The flash_crowd preset's spike window realizes ~mult x the base
    arrival rate (the timeline inversion honors the piecewise rates)."""
    wl = make_preset("flash_crowd", fleet, base_rate=1.0, spike_mult=8.0,
                     horizon_s=1000.0, bin_s=100.0)
    params = SimParams(algo="default_policy",
                       **dict(BASE_KW, duration=1000.0, workload=wl,
                              job_cap=512, queue_cap=8192))
    st0 = init_state(jax.random.key(0), fleet, params)
    eng = Engine(fleet, params)
    pre = eng._pregen_arrivals(st0, 4096)
    tnext = np.asarray(pre["tnext"][0::2])  # inference streams
    finite = tnext[np.isfinite(tnext)]
    # spike is [400, 500): count arrivals per 100 s window across streams
    spike = ((finite >= 400) & (finite < 500)).sum()
    calm = ((finite >= 100) & (finite < 200)).sum()
    assert spike > 4 * max(calm, 1), (spike, calm)


def test_signals_columns_and_accrual(fleet, tmp_path):
    """Signal timelines add the price/carbon cluster columns, accrue
    cost/carbon next to the energy integral, and surface the totals in
    the evaluation summary (-> run_summary.json)."""
    from distributed_cluster_gpus_tpu.evaluation import _summarize

    wl = make_preset("flash_crowd", fleet, base_rate=1.0, horizon_s=300.0)
    params = SimParams(algo="carbon_cost",
                       **dict(BASE_KW, duration=300.0, workload=wl,
                              queue_cap=2048))
    out = str(tmp_path / "sig")
    st = run_simulation(fleet, params, out_dir=out, chunk_steps=4096)
    header = open(os.path.join(out, "cluster_log.csv")).readline().strip()
    assert header.endswith("price_usd_kwh,carbon_g_kwh"), header
    row = open(os.path.join(out, "cluster_log.csv")).readlines()[1]
    price = float(row.strip().split(",")[-2])
    assert 0.0 < price < 1.0, price
    cost = float(np.asarray(st.signals.cost_usd).sum())
    carbon = float(np.asarray(st.signals.carbon_g).sum())
    assert cost > 0 and carbon > 0
    # cost must be consistent with the energy total at tariff bounds
    kwh = float(np.asarray(st.dc.energy_j).sum()) / 3.6e6
    assert 0.8 * 0.12 * kwh <= cost <= 1.2 * 0.20 * kwh, (cost, kwh)
    s = _summarize(params.algo, fleet, st)
    assert s.row()["energy_cost_usd"] == pytest.approx(cost)
    assert s.row()["carbon_kg"] == pytest.approx(carbon / 1e3)


def test_signals_legacy_equivalence(fleet):
    """The legacy_signals preset lifts the static hourly price / per-DC
    carbon tables into timelines; sampled values are identical, so the
    realized schedule matches the plain run (same workload chain, same
    admissions) — counts exactly, accumulators to float tolerance."""
    base = SimParams(algo="carbon_cost", **BASE_KW)
    wl = make_preset("legacy_signals", fleet, params=base)
    withsig = dataclasses.replace(base, workload=wl)
    st_a = run_simulation(fleet, base, out_dir=None, chunk_steps=4096)
    st_b = run_simulation(fleet, withsig, out_dir=None, chunk_steps=4096)
    assert int(st_a.n_events) == int(st_b.n_events)
    assert np.array_equal(np.asarray(st_a.n_finished),
                          np.asarray(st_b.n_finished))
    np.testing.assert_allclose(np.asarray(st_a.dc.energy_j),
                               np.asarray(st_b.dc.energy_j), rtol=1e-6)
    # the legacy price is 0.12-0.20 USD/kWh: the accrued cost must sit
    # inside the energy total's tariff envelope
    kwh = float(np.asarray(st_b.dc.energy_j).sum()) / 3.6e6
    cost = float(np.asarray(st_b.signals.cost_usd).sum())
    assert 0.12 * kwh * 0.99 <= cost <= 0.20 * kwh * 1.01


def test_observed_signals_extend_obs(fleet):
    """SimParams.obs_dim grows by 1 + n_dc when the spec observes its
    signals, and the engine's obs vector matches that width."""
    wl_obs = make_preset("flash_crowd", fleet, horizon_s=300.0,
                         observe=True)
    wl_blind = make_preset("flash_crowd", fleet, horizon_s=300.0)
    base = SimParams(algo="chsac_af", **dict(BASE_KW, duration=300.0))
    p_obs = dataclasses.replace(base, workload=wl_obs)
    p_blind = dataclasses.replace(base, workload=wl_blind)
    n_dc = fleet.n_dc
    assert p_blind.obs_dim(n_dc) == 1 + 6 * n_dc
    assert p_obs.obs_dim(n_dc) == 1 + 6 * n_dc + 1 + n_dc
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)

    cfg = SACConfig(obs_dim=p_obs.obs_dim(n_dc), n_dc=n_dc,
                    n_g=p_obs.max_gpus_per_job,
                    constraints=default_constraints(500.0))
    eng = Engine(fleet, p_obs, policy_apply=make_policy_apply(cfg))
    st = init_state(jax.random.key(0), fleet, p_obs)
    assert eng._obs(st).shape == (p_obs.obs_dim(n_dc),)
    assert st.jobs.rl_obs0.shape[1] == p_obs.obs_dim(n_dc)


def test_obs_registry_signal_metrics(fleet):
    """Signal-enabled runs extend the obs metric registry by the four
    signal metrics; signals-off registries are unchanged (same
    compile-gating contract as fault_only)."""
    from distributed_cluster_gpus_tpu.obs.metrics import (
        registry_for, registry_width)

    wl = make_preset("flash_crowd", fleet, horizon_s=300.0)
    base = SimParams(algo="joint_nf", obs_enabled=True,
                     **dict(BASE_KW, duration=300.0))
    with_wl = dataclasses.replace(base, workload=wl)
    names_off = {e.spec.name for e in registry_for(fleet, base)}
    names_on = {e.spec.name for e in registry_for(fleet, with_wl)}
    added = names_on - names_off
    assert added == {"obs_price_usd_per_kwh", "obs_carbon_g_per_kwh",
                     "obs_energy_cost_usd_total",
                     "obs_carbon_emitted_g_total"}
    n_dc = fleet.n_dc
    assert (registry_width(registry_for(fleet, with_wl))
            == registry_width(registry_for(fleet, base)) + 1 + 3 * n_dc)


# ---------------------------------------------------------------------------
# spec files + validator (scripts/validate_workload.py)
# ---------------------------------------------------------------------------

def _validator():
    spec = importlib.util.spec_from_file_location(
        "validate_workload",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "validate_workload.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(tmp_path, name, doc):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        json.dump(doc, f)
    return p


GOOD_SPEC = {
    "name": "good",
    "streams": {
        "inference": {"kind": "rate_timeline",
                      "rates": [1.0, 3.0, 0.5], "bin_s": 600.0,
                      "periodic": True},
        "training": {"kind": "poisson", "rate": 0.05},
    },
    "signals": {"price": [0.1, 0.2], "bin_s": 43200.0, "periodic": True},
}


def test_workload_json_roundtrip(fleet, tmp_path):
    """Spec files load into runnable WorkloadSpecs; per-ingress entries
    resolve fleet ingress names."""
    path = _write(tmp_path, "good.json", GOOD_SPEC)
    spec = load_workload_json(path, fleet)
    assert spec.streams[0].kind == "rate_timeline"
    params = SimParams(algo="default_policy",
                       **dict(BASE_KW, workload=spec))
    st = run_simulation(fleet, params, out_dir=None, chunk_steps=2048)
    assert int(st.n_events) > 0
    # per-ingress list form with a named ingress
    doc = {"streams": [
        {"ingress": fleet.ingress_names[0],
         "inference": {"kind": "poisson", "rate": 2.0}},
    ]}
    spec2 = load_workload_json(_write(tmp_path, "per_ing.json", doc), fleet)
    resolved = spec2.resolve(fleet.n_ing)
    assert resolved[0][0].kind == "poisson"
    assert all(p[0].kind == "off" for p in resolved[1:])


def test_validate_workload_accepts_good_spec(fleet, tmp_path):
    v = _validator()
    path = _write(tmp_path, "good.json", GOOD_SPEC)
    assert v.lint_spec(path, fleet) == []
    assert v.main([path]) == 0


def test_validate_workload_negative_cases(fleet, tmp_path):
    """The satellite's negative-case pin: malformed specs FAIL the lint
    with a pointed message — non-monotone trace timestamps, non-finite
    rates, wrong carbon shape, unresolved ingress names, unknown keys."""
    v = _validator()
    cases = {
        "trace_backwards": (
            {"streams": {"inference": {"kind": "trace",
                                       "times": [1.0, 3.0, 2.0]}}},
            "non-decreasing"),
        "bad_rate": (
            {"streams": {"inference": {"kind": "poisson", "rate": -2.0}}},
            "rate"),
        "bad_carbon_shape": (
            {"streams": {"inference": {"kind": "poisson", "rate": 1.0}},
             "signals": {"carbon": [[100.0, 200.0]]}},
            "carbon"),
        "unknown_key": (
            {"streams": {"inference": {"kind": "poisson", "rate": 1.0,
                                       "burstiness": 3}}},
            "unknown"),
        "misspelled_stream": (
            # a typo'd jtype key must FAIL, not silently drop the stream
            {"streams": {"inference": {"kind": "poisson", "rate": 1.0},
                         "trainng": {"kind": "poisson", "rate": 0.3}}},
            "unknown stream-section keys"),
        "zero_periodic_timeline": (
            {"streams": {"inference": {"kind": "rate_timeline",
                                       "rates": [0.0, 0.0],
                                       "periodic": True}}},
            "positive total rate"),
    }
    for name, (doc, needle) in cases.items():
        path = _write(tmp_path, f"{name}.json", doc)
        errs = v.lint_spec(path, fleet)
        assert errs, f"{name}: lint accepted a malformed spec"
        assert any(needle in e for e in errs), (name, errs)
        assert v.main([path]) == 1
    # unresolved ingress name (list form)
    path = _write(tmp_path, "bad_ing.json", {"streams": [
        {"ingress": "gw-nowhere",
         "inference": {"kind": "poisson", "rate": 1.0}}]})
    errs = v.lint_spec(path, fleet)
    assert errs and any("ingress" in e for e in errs), errs


# ---------------------------------------------------------------------------
# the acceptance run: week horizon, J = 8192, one scan
# ---------------------------------------------------------------------------

def test_week_scale_one_scan_j8192(fleet, tmp_path):
    """ROADMAP item 5 / round-10 acceptance: a week-long trace-driven run
    (diurnal multi-region peaks + flash crowds + correlated training
    surges + weekly price / diurnal carbon timelines) at J=8192 streams
    through run_simulation as ONE scan chunk, with the price/carbon
    columns in cluster_log and the cost/carbon totals in the summary."""
    from jax.experimental import enable_x64

    with enable_x64():
        wl = make_preset("diurnal_flash_week", fleet, base_rate=0.02,
                         trn_rate=0.002)
        params = SimParams(
            algo="eco_route", duration=7 * 86400.0, log_interval=3600.0,
            workload=wl, job_cap=8192, queue_cap=65536,
            time_dtype="float64", seed=7)
        out = str(tmp_path / "week")
        st = run_simulation(fleet, params, out_dir=out,
                            chunk_steps=400_000, max_chunks=1)
        assert bool(st.done), (
            "the week run did not finish inside ONE chunk "
            f"(t={float(st.t):.0f}s, events={int(st.n_events)})")
        assert float(st.t) >= 7 * 86400.0
        assert int(st.n_events) > 50_000
        header = open(os.path.join(out, "cluster_log.csv")).readline()
        assert "price_usd_kwh" in header and "carbon_g_kwh" in header
        assert float(np.asarray(st.signals.cost_usd).sum()) > 0
        from distributed_cluster_gpus_tpu.evaluation import _summarize

        row = _summarize(params.algo, fleet, st).row()
        assert row["energy_cost_usd"] > 0 and row["carbon_kg"] > 0

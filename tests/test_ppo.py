"""PPO variant: masked clipped-surrogate update + sharded on-policy trainer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.rl.cmdp import N_COSTS, default_constraints
from distributed_cluster_gpus_tpu.rl.ppo import (
    PPOConfig, make_ppo_policy_apply, ppo_init, ppo_update,
)


def cfg_small():
    return PPOConfig(obs_dim=13, n_dc=3, n_g=4, latent=32, epochs=2,
                     constraints=default_constraints(500.0))


def fake_batch(key, n, cfg, p_valid=0.6):
    ks = jax.random.split(key, 8)
    return {
        "valid": jax.random.uniform(ks[0], (n,)) < p_valid,
        "s0": jax.random.normal(ks[1], (n, cfg.obs_dim)),
        "s1": jnp.zeros((n, cfg.obs_dim)),
        "a_dc": jax.random.randint(ks[2], (n,), 0, cfg.n_dc),
        "a_g": jax.random.randint(ks[3], (n,), 0, cfg.n_g),
        "r": jax.random.normal(ks[4], (n,)),
        "costs": jnp.abs(jax.random.normal(ks[5], (n, N_COSTS))),
        "mask_dc": jnp.ones((n, cfg.n_dc), bool),
        "mask_g": jnp.ones((n, cfg.n_g), bool),
        "mask_dc0": jnp.ones((n, cfg.n_dc), bool),
        "mask_g0": jnp.ones((n, cfg.n_g), bool),
    }


def test_update_finite_and_moves_params():
    cfg = cfg_small()
    ppo = ppo_init(cfg, jax.random.key(0))
    batch = fake_batch(jax.random.key(1), 64, cfg)
    ppo2, m = jax.jit(lambda p, b: ppo_update(cfg, p, b))(ppo, batch)
    for k in ("loss", "pg_loss", "vf_loss", "entropy"):
        assert np.isfinite(float(m[k])), k
    assert int(ppo2.step) == 1
    d = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                     ppo.actor_params, ppo2.actor_params)
    assert max(jax.tree.leaves(d)) > 0


def test_invalid_rows_carry_no_gradient():
    """An all-invalid batch must leave params untouched (zero weights)."""
    cfg = cfg_small()
    ppo = ppo_init(cfg, jax.random.key(0))
    batch = fake_batch(jax.random.key(1), 32, cfg, p_valid=0.0)
    batch["valid"] = jnp.zeros((32,), bool)
    ppo2, m = ppo_update(cfg, ppo, batch)
    assert float(m["n_transitions"]) == 0.0
    d = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                     ppo.actor_params, ppo2.actor_params)
    assert max(jax.tree.leaves(d)) == pytest.approx(0.0, abs=1e-7)


def test_entropy_healthy_at_init():
    """Normalized observations must keep the fresh policy near-uniform."""
    cfg = cfg_small()
    ppo = ppo_init(cfg, jax.random.key(0))
    pa = make_ppo_policy_apply(cfg)
    picks = set()
    for i in range(30):
        a_dc, a_g = pa(ppo, jnp.zeros(cfg.obs_dim) + 0.3,
                       jnp.ones(cfg.n_dc, bool), jnp.ones(cfg.n_g, bool),
                       jax.random.key(i))
        picks.add((int(a_dc), int(a_g)))
    assert len(picks) > 5  # near-deterministic policies pick ~1 joint action


def test_sharded_ppo_trainer(fleet):
    from distributed_cluster_gpus_tpu.parallel import make_mesh
    from distributed_cluster_gpus_tpu.parallel.rollout import PPOTrainer

    params = SimParams(algo="chsac_af", duration=120.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=4.0,
                       trn_mode="poisson", trn_rate=0.1,
                       job_cap=64, lat_window=128, seed=5)
    tr = PPOTrainer(fleet, params, n_rollouts=16, mesh=make_mesh())
    m = tr.train_chunk(chunk_steps=48)
    assert int(m["n_events"]) == 16 * 48
    assert np.isfinite(float(m["loss"]))
    assert float(m["n_transitions"]) > 0
    # replicated params stay bit-identical across devices
    leaf = jax.tree.leaves(tr.ppo.actor_params)[0]
    shards = leaf.addressable_shards
    np.testing.assert_array_equal(np.asarray(shards[0].data),
                                  np.asarray(shards[-1].data))

"""Statistical tests of the arrival processes and job-size distributions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.ops.arrivals import (
    JTYPE_INFERENCE,
    JTYPE_TRAINING,
    MODE_OFF,
    MODE_POISSON,
    MODE_SINUSOID,
    ArrivalParams,
    lambda_t,
    next_interarrival,
    sample_job_size,
)


def params(mode, rate, amp=0.0, period=3600.0):
    return ArrivalParams(
        mode=jnp.int32(mode),
        rate=jnp.float32(rate),
        amp=jnp.float32(amp),
        period=jnp.float32(period),
    )


def test_lambda_t_poisson_constant():
    p = params(MODE_POISSON, 6.0)
    assert float(lambda_t(p, 0.0)) == pytest.approx(6.0)
    assert float(lambda_t(p, 123.4)) == pytest.approx(6.0)


def test_lambda_t_sinusoid_shape():
    p = params(MODE_SINUSOID, 6.0, amp=0.6, period=300.0)
    assert float(lambda_t(p, 75.0)) == pytest.approx(6.0 * 1.6, rel=1e-5)  # peak
    assert float(lambda_t(p, 225.0)) == pytest.approx(6.0 * 0.4, rel=1e-5)  # trough
    # clipped at zero for amp > 1
    p2 = params(MODE_SINUSOID, 6.0, amp=1.5, period=300.0)
    assert float(lambda_t(p2, 225.0)) == 0.0


def test_lambda_t_off():
    assert float(lambda_t(params(MODE_OFF, 6.0), 10.0)) == 0.0


def test_off_interarrival_infinite():
    gap = next_interarrival(jax.random.key(0), params(MODE_OFF, 6.0), 0.0)
    assert np.isinf(float(gap))


def test_poisson_interarrival_mean():
    p = params(MODE_POISSON, 2.0)
    keys = jax.random.split(jax.random.key(1), 20000)
    gaps = jax.vmap(lambda k: next_interarrival(k, p, 0.0))(keys)
    m = float(jnp.mean(gaps))
    assert m == pytest.approx(0.5, rel=0.05)


def test_sinusoid_thinning_rate_tracks_lambda():
    # Generate a long stream sequentially and check counts near peak vs trough.
    p = params(MODE_SINUSOID, 5.0, amp=0.8, period=200.0)

    def gen(carry, k):
        t = carry
        gap = next_interarrival(k, p, t)
        return t + gap, t + gap

    keys = jax.random.split(jax.random.key(2), 40000)
    _, times = jax.lax.scan(gen, jnp.float32(0.0), keys)
    times = np.asarray(times)
    phase = times % 200.0
    # peak window around t=50 (sin=1), trough around t=150 (sin=-1)
    peak = ((phase > 30) & (phase < 70)).sum()
    trough = ((phase > 130) & (phase < 170)).sum()
    expected_ratio = (5.0 * 1.8) / (5.0 * 0.2)
    assert peak / max(trough, 1) == pytest.approx(expected_ratio, rel=0.3)


def test_inversion_monotone_and_exact():
    # sinusoid_gap_from_cum must invert the closed-form integrated rate:
    # feeding back delta(s) into the integral recovers s, and arrival
    # times are non-decreasing (the engine's pregen table relies on both)
    from distributed_cluster_gpus_tpu.ops.arrivals import sinusoid_gap_from_cum

    p = params(MODE_SINUSOID, 5.0, amp=0.8, period=200.0)
    cum = jnp.cumsum(jax.random.exponential(jax.random.key(2), (20000,)))
    t0 = jnp.float32(123.4)
    delta = sinusoid_gap_from_cum(p, t0, cum)
    times = np.asarray(t0 + delta, dtype=np.float64)
    assert np.all(np.diff(times) >= 0)
    r, a, P = 5.0, 0.8, 200.0
    w = 2 * np.pi / P
    ph0 = w * (float(t0) % P)
    d = np.asarray(delta, dtype=np.float64)
    s_back = r * d + (r * a / w) * (np.cos(ph0) - np.cos(ph0 + w * d))
    rel = np.abs(s_back - np.asarray(cum, np.float64)) / np.maximum(
        np.asarray(cum, np.float64), 1.0)
    assert rel.max() < 1e-4


def test_inversion_rate_profile_matches_thinning():
    # the inversion sampler and the thinning sampler target the same NHPP:
    # windowed peak/trough counts must agree (same tolerance the thinning
    # test uses against the analytic profile)
    from distributed_cluster_gpus_tpu.ops.arrivals import sinusoid_gap_from_cum

    p = params(MODE_SINUSOID, 5.0, amp=0.8, period=200.0)
    cum = jnp.cumsum(jax.random.exponential(jax.random.key(7), (40000,)))
    times = np.asarray(sinusoid_gap_from_cum(p, jnp.float32(0.0), cum))
    phase = times % 200.0
    peak = ((phase > 30) & (phase < 70)).sum()
    trough = ((phase > 130) & (phase < 170)).sum()
    # exact windowed expectation: mean lambda over +-20 s around peak/trough
    expected = (5.0 * 1.8) / (5.0 * 0.2)
    assert peak / max(trough, 1) == pytest.approx(expected, rel=0.3)


def test_inversion_amp_zero_is_linear():
    from distributed_cluster_gpus_tpu.ops.arrivals import sinusoid_gap_from_cum

    p = params(MODE_SINUSOID, 2.0, amp=0.0, period=300.0)
    d = sinusoid_gap_from_cum(p, jnp.float32(50.0),
                              jnp.asarray([1.0, 10.0], jnp.float32))
    np.testing.assert_allclose(np.asarray(d), [0.5, 5.0], rtol=1e-5)


def test_job_sizes_inference_pareto():
    keys = jax.random.split(jax.random.key(3), 20000)
    sizes = np.asarray(jax.vmap(lambda k: sample_job_size(k, JTYPE_INFERENCE))(keys))
    assert sizes.min() >= 1.0
    # Pareto(1, 1.8) mean = alpha/(alpha-1) = 2.25
    assert sizes.mean() == pytest.approx(2.25, rel=0.15)
    # median = 2^(1/1.8)
    assert np.median(sizes) == pytest.approx(2 ** (1 / 1.8), rel=0.05)


def test_job_sizes_training_lognormal():
    keys = jax.random.split(jax.random.key(4), 20000)
    sizes = np.asarray(jax.vmap(lambda k: sample_job_size(k, JTYPE_TRAINING))(keys))
    assert sizes.min() >= 0.1
    assert np.median(sizes) == pytest.approx(50000.0, rel=0.05)
    logs = np.log(sizes)
    assert logs.std() == pytest.approx(0.4, rel=0.1)


def test_vmapped_clock_matrix():
    # refresh a whole [n_ing, 2] clock matrix in one call
    p = ArrivalParams(
        mode=jnp.asarray([[MODE_POISSON, MODE_POISSON]] * 8, dtype=jnp.int32),
        rate=jnp.full((8, 2), 3.0, dtype=jnp.float32),
        amp=jnp.zeros((8, 2), dtype=jnp.float32),
        period=jnp.full((8, 2), 300.0, dtype=jnp.float32),
    )
    keys = jax.random.split(jax.random.key(5), 16).reshape(8, 2)
    gaps = jax.vmap(jax.vmap(next_interarrival, in_axes=(0, 0, None)), in_axes=(0, 0, None))(
        keys, p, 0.0
    )
    assert gaps.shape == (8, 2)
    assert bool(jnp.all(gaps > 0))

"""Graceful SIGTERM/SIGINT shutdown (utils/shutdown.py + host loops).

Contract (PR 8 satellite): a signal mid-run stops the loop at the next
chunk boundary, flushes the AsyncLineDrain/ObsSink pipelines (the
interrupted run's CSV bytes are an exact PREFIX of an uninterrupted
run's), saves the checkpoint (trainers), writes run_summary.json with
status="interrupted", and the CLI exits nonzero (128 + signum).

The in-process tests are deterministic: the signal is raised from the
loop's own hooks (serial path) or pre-latched (pipelined path).  The
subprocess test drives the real CLI and is slow-tier.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from distributed_cluster_gpus_tpu.configs.paper import build_duo_fleet
from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.sim.io import run_simulation
from distributed_cluster_gpus_tpu.utils.shutdown import (ShutdownFlag,
                                                         defer_signals,
                                                         graceful_shutdown)

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(scope="module")
def duo_fleet():
    return build_duo_fleet()


DUO_KW = dict(
    algo="default_policy", duration=90.0, log_interval=5.0,
    inf_mode="poisson", inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
    job_cap=128, queue_cap=256, seed=11,
)


# ---------------------------------------------------------------------------
# flag + handler mechanics
# ---------------------------------------------------------------------------

def test_shutdown_flag_latches_and_exit_code():
    f = ShutdownFlag()
    assert not f and f.exit_code == 0
    f.trip(signal.SIGTERM)
    f.trip(signal.SIGINT)  # second signal keeps the first signum
    assert f and f.signum == signal.SIGTERM
    assert f.exit_code == 128 + signal.SIGTERM


def test_graceful_shutdown_catches_and_restores():
    before = signal.getsignal(signal.SIGTERM)
    with graceful_shutdown() as flag:
        assert not flag.requested
        os.kill(os.getpid(), signal.SIGTERM)  # would kill us if uncaught
        for _ in range(100):
            if flag.requested:
                break
            time.sleep(0.01)
        assert flag.requested and flag.signum == signal.SIGTERM
        # the handler swapped itself back out: a second delivery would
        # take the previous disposition (the operator's escape hatch)
        assert signal.getsignal(signal.SIGTERM) is before
    assert signal.getsignal(signal.SIGTERM) is before


def test_defer_signals_blocks_delivery_until_exit():
    """The checkpoint-commit critical section (PR 12 satellite): a signal
    sent inside the deferred block is NOT delivered until the block
    exits — so the operator's second SIGTERM (which takes the default
    kill disposition after the graceful latch) lands between commits,
    never mid-commit.

    A live worker thread runs during the block: the trainers always have
    drain/exporter daemon threads, and the kernel may hand the signal to
    ANY thread with it unblocked — so an OS-sigmask deferral of only the
    main thread does not defer at all (CPython still runs the handler on
    the main thread).  The Python-handler-level deferral must hold
    regardless of which thread receives the signal."""
    import threading

    stop = threading.Event()
    worker = threading.Thread(target=stop.wait, daemon=True)
    worker.start()
    got = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: got.append(s))
    try:
        with defer_signals((signal.SIGTERM,)):
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.05)
            assert got == [], "delivery must be deferred inside the block"
        for _ in range(200):
            if got:
                break
            time.sleep(0.01)
        assert got == [signal.SIGTERM], "the deferred signal must be " \
            "delivered when the block exits"
    finally:
        signal.signal(signal.SIGTERM, prev)
        stop.set()
        worker.join()


def test_defer_signals_noop_off_main_thread():
    import threading

    ran = []

    def worker():
        with defer_signals():
            ran.append(True)

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert ran == [True]


def test_save_checkpoint_defers_signal_across_commit(tmp_path):
    """A SIGTERM delivered mid-save is held until the commit finishes:
    the store ends up with the step fully committed AND the handler
    fired after."""
    import numpy as np

    from distributed_cluster_gpus_tpu.utils.checkpoint import (
        latest_step, save_checkpoint, verify_checkpoint)

    got = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: got.append(s))
    try:
        # the signal is pending before the save begins; the save's
        # deferral window must hold it until the rename committed
        os.kill(os.getpid(), signal.SIGTERM)
        # (delivered immediately — outside any deferral — so latch a
        # second one inside via a crash-free save)
        got.clear()

        real_rename = os.rename
        fired = []

        def rename_with_signal(src, dst):
            if not fired:
                fired.append(True)
                os.kill(os.getpid(), signal.SIGTERM)
                time.sleep(0.02)
                assert got == [], "signal must be deferred mid-commit"
            return real_rename(src, dst)

        os.rename = rename_with_signal
        try:
            d = save_checkpoint(str(tmp_path), 1, a=np.arange(4))
        finally:
            os.rename = real_rename
        verify_checkpoint(d)
        assert latest_step(str(tmp_path), verified=True) == 1
        for _ in range(200):
            if got:
                break
            time.sleep(0.01)
        assert got == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_graceful_shutdown_inert_off_main_thread():
    import threading

    out = {}

    def worker():
        with graceful_shutdown() as flag:
            out["flag"] = flag

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert not out["flag"].requested  # inert, but present


# ---------------------------------------------------------------------------
# host loops: stop at the chunk boundary, flush, stamp the status
# ---------------------------------------------------------------------------

def _read(path):
    with open(path, "rb") as f:
        return f.read()


def test_run_simulation_sigterm_serial_loop_prefix_bytes(duo_fleet,
                                                         tmp_path):
    """Serial (on_chunk) loop: SIGTERM raised from inside chunk 1 stops
    the run at that boundary; the flushed CSVs byte-equal a PREFIX of
    the uninterrupted run's, and run_summary.json says interrupted."""
    params = SimParams(**DUO_KW)
    full = str(tmp_path / "full")
    run_simulation(duo_fleet, params, out_dir=full, chunk_steps=128,
                   on_chunk=lambda s, e: None)

    part = str(tmp_path / "part")
    chunks = []

    def on_chunk(state, emissions):
        chunks.append(1)
        if len(chunks) == 1:
            os.kill(os.getpid(), signal.SIGTERM)

    with graceful_shutdown() as flag:
        state = run_simulation(duo_fleet, params, out_dir=part,
                               chunk_steps=128, on_chunk=on_chunk,
                               shutdown=flag)
    assert flag.requested
    assert len(chunks) == 1, "the loop must stop at the next boundary"
    assert not bool(state.done)

    for name in ("cluster_log.csv", "job_log.csv"):
        partial, complete = _read(f"{part}/{name}"), _read(f"{full}/{name}")
        assert len(partial) < len(complete), name
        assert complete.startswith(partial), (
            f"{name}: interrupted bytes are not a prefix of the full "
            "run's — the flush lost or reordered rows")
    rs = json.load(open(os.path.join(part, "run_summary.json")))
    assert rs["status"] == "interrupted"
    assert rs["algo"] == "default_policy"
    assert rs["totals"]["completed_inf"] >= 0


def test_run_simulation_sigterm_pipelined_loop(duo_fleet, tmp_path):
    """Pipelined loop (no hook): a pre-latched flag stops after the
    first chunk, the in-flight tail chunk is flushed, and the ObsSink
    stamps the interrupted summary."""
    from distributed_cluster_gpus_tpu.obs.export import ObsConfig

    params = SimParams(obs_enabled=True, **DUO_KW)
    full = str(tmp_path / "full")
    run_simulation(duo_fleet, params, out_dir=full, chunk_steps=128,
                   obs=ObsConfig(out_dir=full, watchdog="warn"))

    part = str(tmp_path / "part")
    flag = ShutdownFlag()
    flag.trip(signal.SIGTERM)
    state = run_simulation(duo_fleet, params, out_dir=part, chunk_steps=128,
                           obs=ObsConfig(out_dir=part, watchdog="warn"),
                           shutdown=flag)
    assert not bool(state.done)
    for name in ("cluster_log.csv", "job_log.csv", "metrics.jsonl"):
        partial, complete = _read(f"{part}/{name}"), _read(f"{full}/{name}")
        assert 0 < len(partial) < len(complete), name
        assert complete.startswith(partial), name
    rs = json.load(open(os.path.join(part, "run_summary.json")))
    assert rs["status"] == "interrupted"
    full_rs = json.load(open(os.path.join(full, "run_summary.json")))
    assert full_rs["status"] == "completed"


def test_trainer_sigterm_saves_checkpoint_and_status(duo_fleet, tmp_path):
    """train_chsac: an interrupted run saves an off-cadence checkpoint
    at the stopping chunk and stamps the interrupted summary (slow:
    compiles the chsac engine)."""
    from distributed_cluster_gpus_tpu.rl.train import train_chsac
    from distributed_cluster_gpus_tpu.utils.checkpoint import latest_step

    params = SimParams(**{**DUO_KW, "algo": "chsac_af",
                          "rl_warmup": 64, "rl_batch": 32,
                          "duration": 60.0})
    out = str(tmp_path / "run")
    ck = str(tmp_path / "ck")
    flag = ShutdownFlag()

    def on_chunk(chunk, state, history):
        if chunk == 0:
            flag.trip(signal.SIGTERM)

    state, agent, _ = train_chsac(
        duo_fleet, params, out_dir=out, chunk_steps=512,
        ckpt_dir=ck, ckpt_every_chunks=50, on_chunk=on_chunk,
        shutdown=flag)
    assert not bool(state.done)
    # the stop saved an off-cadence checkpoint at the stopping chunk
    assert latest_step(ck) == 0
    rs = json.load(open(os.path.join(out, "run_summary.json")))
    assert rs["status"] == "interrupted"


# ---------------------------------------------------------------------------
# CLI e2e (slow tier): the real process exits 128+SIGTERM with artifacts
# ---------------------------------------------------------------------------

def test_run_sim_cli_sigterm_exits_nonzero(tmp_path):
    """Drive run_sim.py, SIGTERM it mid-run, and check the contract:
    nonzero exit (143), interrupted run_summary.json, parseable CSVs."""
    out = str(tmp_path / "cli")
    repo = os.path.join(HERE, os.pardir)
    cmd = [sys.executable, os.path.join(repo, "run_sim.py"),
           "--algo", "default_policy", "--single-dc",
           "--duration", "86400", "--log-interval", "5",
           "--inf-mode", "poisson", "--inf-rate", "2",
           "--trn-mode", "off", "--chunk-steps", "64",
           "--time-dtype", "float32",
           "--out", out, "--quiet"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(cmd, cwd=repo, env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        cl = os.path.join(out, "cluster_log.csv")
        deadline = time.time() + 600
        # wait until at least one chunk has drained (file grows past the
        # header), then interrupt — the run itself spans ~1400 chunks,
        # so the signal lands mid-run with enormous margin
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            if os.path.exists(cl) and os.path.getsize(cl) > 256:
                break
            time.sleep(0.05)
        assert proc.poll() is None, (
            "run finished before the signal window opened:\n"
            + proc.stdout.read().decode(errors="replace"))
        proc.send_signal(signal.SIGTERM)
        out_b, _ = proc.communicate(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    text = out_b.decode(errors="replace")
    assert proc.returncode == 128 + signal.SIGTERM, (proc.returncode, text)
    assert "interrupted by signal" in text
    rs = json.load(open(os.path.join(out, "run_summary.json")))
    assert rs["status"] == "interrupted"
    # flushed CSVs parse cleanly and end on a complete row
    data = _read(cl)
    assert data.endswith(b"\n") and data.count(b"\n") > 1
    import pandas as pd

    cl_df = pd.read_csv(cl)
    assert (cl_df["time_s"].diff().dropna() >= 0).all()


def test_defer_signals_redelivers_every_arrival_sequentially():
    """Two SIGTERMs inside one deferred block: each re-delivers through
    the disposition current AT THAT POINT — a latch handler that swaps
    itself out on the first delivery (graceful_shutdown's escape hatch)
    leaves the second to the next disposition, so the operator's
    kill intent is never silently dropped."""
    got = []

    def second(signum, frame):
        got.append("second")

    def latch(signum, frame):
        got.append("latch")
        signal.signal(signum, second)

    prev = signal.signal(signal.SIGTERM, latch)
    try:
        with defer_signals((signal.SIGTERM,)):
            os.kill(os.getpid(), signal.SIGTERM)
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.05)
            assert got == []
        assert got == ["latch", "second"]
    finally:
        signal.signal(signal.SIGTERM, prev)

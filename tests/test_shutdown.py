"""Graceful SIGTERM/SIGINT shutdown (utils/shutdown.py + host loops).

Contract (PR 8 satellite): a signal mid-run stops the loop at the next
chunk boundary, flushes the AsyncLineDrain/ObsSink pipelines (the
interrupted run's CSV bytes are an exact PREFIX of an uninterrupted
run's), saves the checkpoint (trainers), writes run_summary.json with
status="interrupted", and the CLI exits nonzero (128 + signum).

The in-process tests are deterministic: the signal is raised from the
loop's own hooks (serial path) or pre-latched (pipelined path).  The
subprocess test drives the real CLI and is slow-tier.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from distributed_cluster_gpus_tpu.configs.paper import build_duo_fleet
from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.sim.io import run_simulation
from distributed_cluster_gpus_tpu.utils.shutdown import (ShutdownFlag,
                                                         graceful_shutdown)

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(scope="module")
def duo_fleet():
    return build_duo_fleet()


DUO_KW = dict(
    algo="default_policy", duration=90.0, log_interval=5.0,
    inf_mode="poisson", inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
    job_cap=128, queue_cap=256, seed=11,
)


# ---------------------------------------------------------------------------
# flag + handler mechanics
# ---------------------------------------------------------------------------

def test_shutdown_flag_latches_and_exit_code():
    f = ShutdownFlag()
    assert not f and f.exit_code == 0
    f.trip(signal.SIGTERM)
    f.trip(signal.SIGINT)  # second signal keeps the first signum
    assert f and f.signum == signal.SIGTERM
    assert f.exit_code == 128 + signal.SIGTERM


def test_graceful_shutdown_catches_and_restores():
    before = signal.getsignal(signal.SIGTERM)
    with graceful_shutdown() as flag:
        assert not flag.requested
        os.kill(os.getpid(), signal.SIGTERM)  # would kill us if uncaught
        for _ in range(100):
            if flag.requested:
                break
            time.sleep(0.01)
        assert flag.requested and flag.signum == signal.SIGTERM
        # the handler swapped itself back out: a second delivery would
        # take the previous disposition (the operator's escape hatch)
        assert signal.getsignal(signal.SIGTERM) is before
    assert signal.getsignal(signal.SIGTERM) is before


def test_graceful_shutdown_inert_off_main_thread():
    import threading

    out = {}

    def worker():
        with graceful_shutdown() as flag:
            out["flag"] = flag

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert not out["flag"].requested  # inert, but present


# ---------------------------------------------------------------------------
# host loops: stop at the chunk boundary, flush, stamp the status
# ---------------------------------------------------------------------------

def _read(path):
    with open(path, "rb") as f:
        return f.read()


def test_run_simulation_sigterm_serial_loop_prefix_bytes(duo_fleet,
                                                         tmp_path):
    """Serial (on_chunk) loop: SIGTERM raised from inside chunk 1 stops
    the run at that boundary; the flushed CSVs byte-equal a PREFIX of
    the uninterrupted run's, and run_summary.json says interrupted."""
    params = SimParams(**DUO_KW)
    full = str(tmp_path / "full")
    run_simulation(duo_fleet, params, out_dir=full, chunk_steps=128,
                   on_chunk=lambda s, e: None)

    part = str(tmp_path / "part")
    chunks = []

    def on_chunk(state, emissions):
        chunks.append(1)
        if len(chunks) == 1:
            os.kill(os.getpid(), signal.SIGTERM)

    with graceful_shutdown() as flag:
        state = run_simulation(duo_fleet, params, out_dir=part,
                               chunk_steps=128, on_chunk=on_chunk,
                               shutdown=flag)
    assert flag.requested
    assert len(chunks) == 1, "the loop must stop at the next boundary"
    assert not bool(state.done)

    for name in ("cluster_log.csv", "job_log.csv"):
        partial, complete = _read(f"{part}/{name}"), _read(f"{full}/{name}")
        assert len(partial) < len(complete), name
        assert complete.startswith(partial), (
            f"{name}: interrupted bytes are not a prefix of the full "
            "run's — the flush lost or reordered rows")
    rs = json.load(open(os.path.join(part, "run_summary.json")))
    assert rs["status"] == "interrupted"
    assert rs["algo"] == "default_policy"
    assert rs["totals"]["completed_inf"] >= 0


def test_run_simulation_sigterm_pipelined_loop(duo_fleet, tmp_path):
    """Pipelined loop (no hook): a pre-latched flag stops after the
    first chunk, the in-flight tail chunk is flushed, and the ObsSink
    stamps the interrupted summary."""
    from distributed_cluster_gpus_tpu.obs.export import ObsConfig

    params = SimParams(obs_enabled=True, **DUO_KW)
    full = str(tmp_path / "full")
    run_simulation(duo_fleet, params, out_dir=full, chunk_steps=128,
                   obs=ObsConfig(out_dir=full, watchdog="warn"))

    part = str(tmp_path / "part")
    flag = ShutdownFlag()
    flag.trip(signal.SIGTERM)
    state = run_simulation(duo_fleet, params, out_dir=part, chunk_steps=128,
                           obs=ObsConfig(out_dir=part, watchdog="warn"),
                           shutdown=flag)
    assert not bool(state.done)
    for name in ("cluster_log.csv", "job_log.csv", "metrics.jsonl"):
        partial, complete = _read(f"{part}/{name}"), _read(f"{full}/{name}")
        assert 0 < len(partial) < len(complete), name
        assert complete.startswith(partial), name
    rs = json.load(open(os.path.join(part, "run_summary.json")))
    assert rs["status"] == "interrupted"
    full_rs = json.load(open(os.path.join(full, "run_summary.json")))
    assert full_rs["status"] == "completed"


def test_trainer_sigterm_saves_checkpoint_and_status(duo_fleet, tmp_path):
    """train_chsac: an interrupted run saves an off-cadence checkpoint
    at the stopping chunk and stamps the interrupted summary (slow:
    compiles the chsac engine)."""
    from distributed_cluster_gpus_tpu.rl.train import train_chsac
    from distributed_cluster_gpus_tpu.utils.checkpoint import latest_step

    params = SimParams(**{**DUO_KW, "algo": "chsac_af",
                          "rl_warmup": 64, "rl_batch": 32,
                          "duration": 60.0})
    out = str(tmp_path / "run")
    ck = str(tmp_path / "ck")
    flag = ShutdownFlag()

    def on_chunk(chunk, state, history):
        if chunk == 0:
            flag.trip(signal.SIGTERM)

    state, agent, _ = train_chsac(
        duo_fleet, params, out_dir=out, chunk_steps=512,
        ckpt_dir=ck, ckpt_every_chunks=50, on_chunk=on_chunk,
        shutdown=flag)
    assert not bool(state.done)
    # the stop saved an off-cadence checkpoint at the stopping chunk
    assert latest_step(ck) == 0
    rs = json.load(open(os.path.join(out, "run_summary.json")))
    assert rs["status"] == "interrupted"


# ---------------------------------------------------------------------------
# CLI e2e (slow tier): the real process exits 128+SIGTERM with artifacts
# ---------------------------------------------------------------------------

def test_run_sim_cli_sigterm_exits_nonzero(tmp_path):
    """Drive run_sim.py, SIGTERM it mid-run, and check the contract:
    nonzero exit (143), interrupted run_summary.json, parseable CSVs."""
    out = str(tmp_path / "cli")
    repo = os.path.join(HERE, os.pardir)
    cmd = [sys.executable, os.path.join(repo, "run_sim.py"),
           "--algo", "default_policy", "--single-dc",
           "--duration", "86400", "--log-interval", "5",
           "--inf-mode", "poisson", "--inf-rate", "2",
           "--trn-mode", "off", "--chunk-steps", "64",
           "--time-dtype", "float32",
           "--out", out, "--quiet"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(cmd, cwd=repo, env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        cl = os.path.join(out, "cluster_log.csv")
        deadline = time.time() + 600
        # wait until at least one chunk has drained (file grows past the
        # header), then interrupt — the run itself spans ~1400 chunks,
        # so the signal lands mid-run with enormous margin
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            if os.path.exists(cl) and os.path.getsize(cl) > 256:
                break
            time.sleep(0.05)
        assert proc.poll() is None, (
            "run finished before the signal window opened:\n"
            + proc.stdout.read().decode(errors="replace"))
        proc.send_signal(signal.SIGTERM)
        out_b, _ = proc.communicate(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    text = out_b.decode(errors="replace")
    assert proc.returncode == 128 + signal.SIGTERM, (proc.returncode, text)
    assert "interrupted by signal" in text
    rs = json.load(open(os.path.join(out, "run_summary.json")))
    assert rs["status"] == "interrupted"
    # flushed CSVs parse cleanly and end on a complete row
    data = _read(cl)
    assert data.endswith(b"\n") and data.count(b"\n") > 1
    import pandas as pd

    cl_df = pd.read_csv(cl)
    assert (cl_df["time_s"].diff().dropna() >= 0).all()

"""Engine correctness: golden closed-form runs, conservation properties, vmap.

Mirrors the reference's own verification toolkit (SURVEY.md §4): the `debug`
algo pins (n, f) so T/P/E are exactly checkable; plus property tests the
reference never had (energy = ∫P dt, job conservation, GPU accounting).
"""

import jax
import numpy as np
import pandas as pd
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
from distributed_cluster_gpus_tpu.sim.io import run_simulation


def run(fleet, tmp_path, **kw):
    params = SimParams(**kw)
    out = str(tmp_path / kw.get("algo", "default_policy"))
    state = run_simulation(fleet, params, out_dir=out, chunk_steps=2048)
    cl = pd.read_csv(out + "/cluster_log.csv")
    jb = pd.read_csv(out + "/job_log.csv")
    return state, cl, jb


DEBUG_KW = dict(
    algo="debug", duration=120.0, log_interval=5.0,
    inf_mode="poisson", inf_rate=2.0, trn_mode="off",
    num_fixed_gpus=1, fixed_freq=1.0, job_cap=256, seed=7,
)


@pytest.fixture(scope="module")
def debug_run(single_dc_fleet, tmp_path_factory):
    return run(single_dc_fleet, tmp_path_factory.mktemp("dbg"), **DEBUG_KW)


def test_debug_exact_latency(debug_run):
    # single-DC inference coeffs: T(1, 1.0) = 0.002 + 0.004 = 0.006 s/unit
    _, _, jb = debug_run
    assert len(jb) > 100
    ratio = jb.latency_s / jb["size"]
    np.testing.assert_allclose(ratio, 0.006, rtol=5e-3)
    np.testing.assert_allclose(jb.T_pred, 0.006, rtol=1e-5)
    # P(1.0) = 95 + 20 + 97 = 212 W
    np.testing.assert_allclose(jb.P_pred, 212.0, rtol=1e-4)
    np.testing.assert_allclose(jb.E_pred, 212.0 * 0.006, rtol=1e-2)
    assert (jb.n_gpus == 1).all()
    assert (jb.f_used == 1.0).all()


def test_debug_energy_integral(debug_run):
    # Energy must equal the idle floor + per-job active energy to ~0.5%.
    state, cl, jb = debug_run
    idle_floor = 128 * 28.0 * 120.0  # all GPUs sleeping the whole run
    # each job: n=1 busy for size*T at P_active(212) instead of sleeping(28)
    active_extra = ((212.0 - 28.0) * jb["size"] * 0.006).sum()
    expected = idle_floor + active_extra
    got = float(state.dc.energy_j[0])
    assert got == pytest.approx(expected, rel=5e-3)
    # cumulative energy in the last cluster row matches state (within last interval)
    assert cl.energy_kJ.iloc[-1] == pytest.approx(got / 1000.0, rel=1e-2)


def test_job_conservation(debug_run):
    state, _, jb = debug_run
    jobs = state.jobs
    live = int((np.asarray(jobs.status) != 0).sum())
    finished = int(np.asarray(state.n_finished).sum())
    dropped = int(state.n_dropped)
    arrivals = int(state.jid_counter) - 1
    assert finished == len(jb)
    assert arrivals == finished + live + dropped


def test_busy_accounting(debug_run):
    state, cl, _ = debug_run
    # at end: busy == sum of running-job n per dc
    jobs = state.jobs
    running = np.asarray(jobs.status) == 3
    n = np.asarray(jobs.n)
    dc = np.asarray(jobs.dc)
    for d in range(len(state.dc.busy)):
        assert int(state.dc.busy[d]) == int(n[running & (dc == d)].sum())
    assert (cl.busy >= 0).all() and (cl.busy <= 128).all()
    assert (cl.busy + cl.free == 128).all()


def test_csv_schemas(debug_run):
    # column sets and semantics are specified in docs/log_schema.md (the
    # English port of the reference's log-schema oracle doc)
    _, cl, jb = debug_run
    assert list(cl.columns) == [
        "time_s", "dc", "freq", "busy", "free", "run_total", "run_inf",
        "run_train", "q_inf", "q_train", "util_inst", "util_avg",
        "acc_job_unit", "power_W", "energy_kJ"]
    assert list(jb.columns) == [
        "jid", "ingress", "type", "size", "dc", "f_used", "n_gpus",
        "net_lat_s", "start_s", "finish_s", "latency_s", "preempt_count",
        "T_pred", "P_pred", "E_pred"]
    assert (jb.type == "inference").all()
    assert (jb.dc == "us-west").all()
    np.testing.assert_allclose(jb.net_lat_s, 0.012, rtol=1e-6)
    # log ticks at each interval
    assert cl.time_s.nunique() == 24


def test_determinism(single_dc_fleet, tmp_path):
    s1, _, j1 = run(single_dc_fleet, tmp_path / "a", **DEBUG_KW)
    s2, _, j2 = run(single_dc_fleet, tmp_path / "b", **DEBUG_KW)
    assert float(s1.dc.energy_j[0]) == float(s2.dc.energy_j[0])
    pd.testing.assert_frame_equal(j1, j2)


def test_joint_nf_matches_grid_argmin(fleet, tmp_path):
    state, _, jb = run(
        fleet, tmp_path, algo="joint_nf", duration=60.0, log_interval=5.0,
        inf_mode="poisson", inf_rate=2.0, trn_mode="off", job_cap=1024, seed=3)
    # every started job must use the precomputed energy-argmin (n*, f*) of its dc
    E = fleet.E_grid  # [n_dc, 2, n_max, n_f]
    for dc_name, grp in jb.groupby("dc"):
        d = fleet.dc_names.index(dc_name)
        flat = np.argmin(E[d, 0].reshape(-1))
        n_star, f_star = flat // 8 + 1, fleet.freq_levels[flat % 8]
        assert (grp.n_gpus == n_star).all()
        np.testing.assert_allclose(grp.f_used, round(float(f_star), 3), atol=1e-3)


def test_carbon_cost_equals_joint_nf_when_price_positive(fleet, tmp_path):
    # global hourly price is always > 0 => cost objective == energy argmin
    kw = dict(duration=60.0, log_interval=5.0, inf_mode="poisson", inf_rate=2.0,
              trn_mode="off", job_cap=1024, seed=3)
    _, _, j1 = run(fleet, tmp_path / "jn", algo="joint_nf", **kw)
    _, _, j2 = run(fleet, tmp_path / "cc", algo="carbon_cost", **kw)
    pd.testing.assert_frame_equal(j1, j2)


def test_default_policy_energy_aware_inference(fleet, tmp_path):
    _, _, jb = run(
        fleet, tmp_path, algo="default_policy", duration=30.0, log_interval=5.0,
        inf_mode="poisson", inf_rate=2.0, trn_mode="off", job_cap=1024, seed=3)
    # energy_aware: inference at dvfs_high = 1.0, n = min(free, 8)
    assert (jb.f_used == 1.0).all()
    assert (jb.n_gpus <= 8).all()
    assert jb.n_gpus.max() == 8


def test_eco_route_routes_to_min_energy_dc(fleet, tmp_path):
    _, _, jb = run(
        fleet, tmp_path, algo="eco_route", duration=30.0, log_interval=5.0,
        inf_mode="poisson", inf_rate=1.0, trn_mode="off", job_cap=1024, seed=3)
    # expected DC: argmin over dc of per-unit energy at that dc's best cell
    E = fleet.E_grid[:, 0].reshape(len(fleet.dc_names), -1)
    best_cell = np.argmin(E, axis=1)
    e_unit = E[np.arange(E.shape[0]), best_cell]
    expect = fleet.dc_names[int(np.argmin(e_unit))]
    assert (jb.dc == expect).all()


def test_cap_greedy_reduces_power(fleet, tmp_path):
    kw = dict(duration=60.0, log_interval=5.0, inf_mode="off",
              trn_mode="poisson", trn_rate=0.05, job_cap=512, seed=5)
    state_cap, cl_cap, _ = run(fleet, tmp_path / "cap", algo="cap_greedy",
                               power_cap=25000.0, **kw)
    state_nc, cl_nc, _ = run(fleet, tmp_path / "nocap", algo="cap_greedy",
                             power_cap=0.0, **kw)
    # With a (here infeasible) cap, the controller drives every running job to
    # the bottom of the DVFS ladder; without it nobody is downclocked.
    jobs = state_cap.jobs
    running = np.asarray(jobs.status) == 3
    assert running.sum() > 0
    assert (np.asarray(jobs.f_idx)[running] == 0).all()
    jobs_nc = state_nc.jobs
    running_nc = np.asarray(jobs_nc.status) == 3
    assert (np.asarray(jobs_nc.f_idx)[running_nc] > 0).all()
    # capped run must never draw more power than the uncapped one at any tick
    p_cap = cl_cap.groupby("time_s").power_W.sum()
    p_nc = cl_nc.groupby("time_s").power_W.sum()
    assert (p_cap <= p_nc + 1e-6).all()


def test_vmap_rollouts_distinct(fleet):
    params = SimParams(algo="default_policy", duration=20.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=2.0, trn_mode="off",
                       job_cap=256, seed=0)
    engine = Engine(fleet, params)
    keys = jax.random.split(jax.random.key(0), 4)
    states = jax.vmap(lambda k: init_state(k, fleet, params))(keys)
    vrun = jax.jit(jax.vmap(lambda s: engine._run_chunk(s, None, 1024)))
    states, _ = vrun(states)
    fin = states.n_finished[:, 0].tolist()
    assert all(f > 0 for f in fin)
    assert len(set(fin)) > 1  # different seeds -> different trajectories


def test_slab_overflow_counts_drops(single_dc_fleet, tmp_path):
    # long-running training jobs (n=1, f=0.3: ~8000 s each) fill a tiny slab.
    # queue_mode="slab" pins the pre-round-4 layout's drop accounting; in
    # the default ring layout the same overflow spills to the rings instead
    # (tests/test_queue_rings.py covers both outcomes)
    state, _, _ = run(
        single_dc_fleet, tmp_path, algo="debug", duration=30.0, log_interval=5.0,
        inf_mode="off", trn_mode="poisson", trn_rate=2.0,
        num_fixed_gpus=1, fixed_freq=0.3, job_cap=8, seed=1,
        queue_mode="slab")
    assert int(state.n_dropped) > 0  # tiny slab must overflow, not crash


def test_grid_admission_honors_gpu_cap(single_dc_fleet, tmp_path):
    """joint_nf's grid argmin must respect max_gpus_per_job (the reference
    bounds best_nf_grid by policy.max_gpus_per_job)."""
    _, _, jb = run(
        single_dc_fleet, tmp_path, algo="joint_nf", duration=40.0,
        log_interval=5.0, inf_mode="poisson", inf_rate=2.0, trn_mode="off",
        max_gpus_per_job=2, job_cap=256, seed=3)
    assert len(jb) > 20
    assert (jb.n_gpus <= 2).all()


def test_reserve_inf_gpus_blocks_training(single_dc_fleet, tmp_path):
    """With reserve_inf_gpus=R, training admissions must leave >= R GPUs
    free per DC (live version of the reference's dead policy.py:13 knob);
    inference may still use them."""
    import jax.numpy as jnp

    from distributed_cluster_gpus_tpu.models import JobStatus, SimParams
    from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

    # training-only flood: debug algo asks for 4 GPUs per job on a 128-GPU DC
    params = SimParams(algo="debug", duration=1e9, log_interval=50.0,
                       inf_mode="off", trn_mode="poisson", trn_rate=5.0,
                       num_fixed_gpus=4, fixed_freq=1.0,
                       reserve_inf_gpus=6, job_cap=256, seed=2)
    eng = Engine(single_dc_fleet, params)
    state = init_state(jax.random.key(0), single_dc_fleet, params)
    total = int(single_dc_fleet.total_gpus[0])
    peak_busy = 0
    step64 = jax.jit(lambda s: eng._run_chunk(s, None, 64)[0])
    for _ in range(40):
        state = step64(state)
        peak_busy = max(peak_busy, int(state.dc.busy[0]))
    # the flood must saturate everything EXCEPT the reserve
    assert peak_busy == total - 6, (peak_busy, total)
    # sanity: jobs actually queue behind the reserve (waiting jobs live in
    # the queue rings since round 4, not the slab)
    q_inf, q_trn = eng._queue_lens(state)
    assert int(jnp.sum(q_inf) + jnp.sum(q_trn)) > 0

    # same flood without the reserve saturates the DC completely
    params0 = SimParams(algo="debug", duration=1e9, log_interval=50.0,
                        inf_mode="off", trn_mode="poisson", trn_rate=5.0,
                        num_fixed_gpus=4, fixed_freq=1.0,
                        reserve_inf_gpus=0, job_cap=256, seed=2)
    eng0 = Engine(single_dc_fleet, params0)
    s0 = init_state(jax.random.key(0), single_dc_fleet, params0)
    step64b = jax.jit(lambda s: eng0._run_chunk(s, None, 64)[0])
    peak0 = 0
    for _ in range(40):
        s0 = step64b(s0)
        peak0 = max(peak0, int(s0.dc.busy[0]))
    assert peak0 == total, (peak0, total)


def test_reserve_inf_gpus_chsac_masks(single_dc_fleet):
    """chsac_af with a reserve: the policy's masks must never offer
    training jobs the reserved GPUs, and training can never occupy them."""
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.rl.cmdp import constraints_from_params
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)
    from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

    params = SimParams(algo="chsac_af", duration=1e9, log_interval=50.0,
                       inf_mode="off", trn_mode="poisson", trn_rate=5.0,
                       reserve_inf_gpus=6, job_cap=256, lat_window=64, seed=4)
    cfg = SACConfig(obs_dim=params.obs_dim(single_dc_fleet.n_dc),
                    n_dc=single_dc_fleet.n_dc, n_g=params.max_gpus_per_job,
                    batch=16, constraints=constraints_from_params(params))
    eng = Engine(single_dc_fleet, params, policy_apply=make_policy_apply(cfg))
    pp = sac_init(cfg, jax.random.key(0))
    state = init_state(jax.random.key(1), single_dc_fleet, params)
    total = int(single_dc_fleet.total_gpus[0])
    step128 = jax.jit(lambda s: eng._run_chunk(s, pp, 128)[0])
    peak = 0
    for _ in range(25):
        state = step128(state)
        peak = max(peak, int(state.dc.busy[0]))
    assert peak <= total - 6, (peak, total)
    assert peak > 0  # training work did run outside the reserve


def test_cached_physics_matches_recompute(fleet, tmp_path):
    """The slab's cached spu/watts must equal T(n, f)/P(n, f) recomputed
    from scratch for every RUNNING row, across algorithms that mutate (n, f)
    through every write site (start, cap_uniform bulk clamp, cap_greedy
    atoms)."""
    from distributed_cluster_gpus_tpu.models import JobStatus
    from distributed_cluster_gpus_tpu.ops.physics import (step_time_s,
                                                          task_power_w)

    cases = [
        dict(algo="joint_nf"),
        dict(algo="cap_uniform", power_cap=25000.0),
        dict(algo="cap_greedy", power_cap=25000.0),
        dict(algo="bandit"),
    ]
    for i, case in enumerate(cases):
        kw = dict(duration=60.0, log_interval=5.0, inf_mode="poisson",
                  inf_rate=2.0, trn_mode="poisson", trn_rate=0.05,
                  job_cap=256, seed=20 + i, **case)
        state, _, _ = run(fleet, tmp_path / case["algo"], **kw)
        eng = Engine(fleet, SimParams(**kw))
        jobs = state.jobs
        pc, tc = eng._job_coeffs(jobs)
        f = eng.freq_levels[jobs.f_idx]
        T = np.asarray(step_time_s(jobs.n, f, tc))
        P = np.asarray(task_power_w(jobs.n, f, pc))
        running = np.asarray(jobs.status) == JobStatus.RUNNING
        assert running.sum() > 0, case
        np.testing.assert_allclose(np.asarray(jobs.spu)[running], T[running],
                                   rtol=1e-6, err_msg=str(case))
        np.testing.assert_allclose(np.asarray(jobs.watts)[running], P[running],
                                   rtol=1e-6, err_msg=str(case))


from conftest import tree_mismatches as _tree_equal



def _fresh(st):
    """Deep-copy a SimState: run_chunk donates its input buffer, so A/B
    tests that feed one initial state to two engines copy per call."""
    import jax.numpy as jnp
    return jax.tree.map(jnp.copy, st)

def test_arrival_pregen_poisson_same_workload(fleet):
    """Pregen backend flag on vs off: Poisson streams compile the same
    per-gap left-fold generator either way (the flag only selects the
    sinusoid backend), so since round 10 the runs are BIT-IDENTICAL —
    strengthened from the historical summation-rounding tolerance (the
    old inversion path re-associated the gap sums; the workload
    compiler's fold reproduces the legacy in-step recursion exactly)."""
    params = SimParams(algo="default_policy", duration=1e9, log_interval=20.0,
                       inf_mode="poisson", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0)
    st0 = init_state(jax.random.key(0), fleet, params)
    eng_on = Engine(fleet, params)
    eng_on.arrival_pregen = True
    eng_off = Engine(fleet, params)
    eng_off.arrival_pregen = False
    s_on, _ = eng_on.run_chunk(_fresh(st0), None, n_steps=512)
    s_off, _ = eng_off.run_chunk(_fresh(st0), None, n_steps=512)
    bad = _tree_equal(s_on, s_off)
    assert not bad, bad


def test_arrival_pregen_scan_fallback_bit_identical(fleet):
    """amp > 1 sinusoid (zero-rate windows) routes to the thinning
    replay backend regardless of the pregen flag — both flag settings
    replay the legacy draw recursion bit-exactly, across chunk
    boundaries (the whole state tree must match)."""
    params = SimParams(algo="default_policy", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, inf_amp=1.5,
                       trn_mode="poisson", trn_rate=0.1, job_cap=128,
                       lat_window=512, seed=0)
    st0 = init_state(jax.random.key(0), fleet, params)
    eng_on = Engine(fleet, params)
    eng_on.arrival_pregen = True
    eng_off = Engine(fleet, params)
    eng_off.arrival_pregen = False
    s_on, _ = eng_on.run_chunk(_fresh(st0), None, n_steps=384)
    s_on, _ = eng_on.run_chunk(s_on, None, n_steps=128)
    s_off, _ = eng_off.run_chunk(_fresh(st0), None, n_steps=384)
    s_off, _ = eng_off.run_chunk(s_off, None, n_steps=128)
    bad = _tree_equal(s_on, s_off)
    assert not bad, bad


def test_arrival_pregen_sinusoid_statistical_match(fleet):
    """The epoch-anchored inversion (default) realizes a different draw
    than the thinning replay (DCG_ARRIVAL_PREGEN=0) for |amp| <= 1
    sinusoid streams but the same process: arrival totals over a
    horizon agree."""
    params = SimParams(algo="default_policy", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0)
    st0 = init_state(jax.random.key(0), fleet, params)
    eng_on = Engine(fleet, params)
    eng_on.arrival_pregen = True
    eng_off = Engine(fleet, params)
    eng_off.arrival_pregen = False
    s_on, _ = eng_on.run_chunk(_fresh(st0), None, n_steps=2048)
    s_off, _ = eng_off.run_chunk(_fresh(st0), None, n_steps=2048)
    n_on, n_off = int(s_on.jid_counter), int(s_off.jid_counter)
    assert abs(n_on - n_off) / max(n_off, 1) < 0.1, (n_on, n_off)


def _ref_cap_greedy_model(job_list, fleet, cap, idle_floor_w):
    """Faithful numpy model of the reference cap_greedy pass
    (`freq_load_agg.py:44-80` atoms + `simulator_paper_multi.py:269-316`
    apply loop): stepwise down-ladder atoms per job, global stable sort by
    rho, apply each atom by jumping the job to the atom's LOWER endpoint
    (skipping atoms whose target is not below the job's current level),
    exact power re-estimation after every applied atom, rebuild until no
    atom applies or the deficit is gone.  Power accounting mirrors the
    engine's `_dc_power` (active job watts + constant idle floor)."""
    import jax

    levels = list(np.asarray(fleet.freq_levels))
    pw = jax.tree.map(np.asarray, fleet.power)
    lt = jax.tree.map(np.asarray, fleet.latency)

    def P(job, f):
        a, b, g = (pw.alpha_p[job["dc"], job["jt"]],
                   pw.beta_p[job["dc"], job["jt"]],
                   pw.gamma_p[job["dc"], job["jt"]])
        return job["n"] * (a * f**3 + b * f + g)

    def V(job, f):
        a, b, g = (lt.alpha_t[job["dc"], job["jt"]],
                   lt.beta_t[job["dc"], job["jt"]],
                   lt.gamma_t[job["dc"], job["jt"]])
        base = a + b / f
        T = base if job["n"] == 1 else (base + g * job["n"]) / job["n"]
        return 1.0 / T

    def total_power():
        return idle_floor_w + sum(P(j, levels[j["f_idx"]]) for j in job_list)

    while True:
        deficit = total_power() - cap
        if deficit <= 1e-6:
            break
        atoms = []
        for ji, job in enumerate(job_list):
            i0 = job["f_idx"]
            curV, curP = V(job, levels[i0]), P(job, levels[i0])
            for k in range(i0, 0, -1):
                V2, P2 = V(job, levels[k - 1]), P(job, levels[k - 1])
                dV, dP = max(0.0, curV - V2), max(0.0, curP - P2)
                if dV > 0 and dP >= 0:
                    atoms.append((dP / dV, ji, k - 1))
                curV, curP = V2, P2
        if not atoms:
            break
        atoms.sort(key=lambda a: a[0])  # python sort is stable
        applied = False
        for rho, ji, tgt in atoms:
            if deficit <= 1e-6:
                break
            if tgt >= job_list[ji]["f_idx"]:
                continue  # not a downclock from the job's CURRENT level
            job_list[ji]["f_idx"] = tgt
            applied = True
            deficit = total_power() - cap
        if not applied:
            break
    return [j["f_idx"] for j in job_list]


@pytest.mark.parametrize("cap_drop_w", [300.0, 3000.0, 30000.0])
def test_cap_greedy_matches_reference_atom_ladder(fleet, cap_drop_w):
    """Engine `_cap_greedy` vs the reference's sorted multi-step atom pass
    on a hand-built multi-job, multi-DC, multi-ladder scenario: the final
    per-job frequency assignment must be identical for shallow, medium and
    deep cap deficits (the deep case exercises the multi-step JUMP —
    cheapest atoms sit at the ladder bottom for the paper coefficients)."""
    import jax
    import jax.numpy as jnp
    from distributed_cluster_gpus_tpu.models import JobStatus

    kw = dict(algo="cap_greedy", duration=100.0, log_interval=5.0,
              inf_mode="off", trn_mode="off", job_cap=16, seed=0)
    scenario = [  # (slot, dc, jt, n, f_idx) — distinct coeffs and ladders
        (0, 0, 0, 2, 7), (1, 0, 1, 8, 7), (2, 1, 0, 1, 7),
        (3, 2, 1, 4, 5), (4, 3, 0, 3, 7), (5, 1, 1, 6, 6),
    ]
    params = SimParams(**kw, power_cap=1.0)  # placeholder; set per case
    eng0 = Engine(fleet, params)
    state = init_state(jax.random.key(0), fleet, params)

    J = params.job_cap
    status = np.zeros(J, np.int32)
    dc = np.zeros(J, np.int32)
    jt = np.zeros(J, np.int32)
    n = np.zeros(J, np.int32)
    f_idx = np.zeros(J, np.int32)
    spu = np.zeros(J, np.float32)
    watts = np.zeros(J, np.float32)
    busy = np.zeros(fleet.n_dc, np.int32)
    for slot, d, t, g, fi in scenario:
        status[slot], dc[slot], jt[slot], n[slot], f_idx[slot] = (
            JobStatus.RUNNING, d, t, g, fi)
        T, P = eng0._row_TP(jnp.int32(d), jnp.int32(t), jnp.int32(g),
                            jnp.int32(fi))
        spu[slot], watts[slot] = float(T), float(P)
        busy[d] += g
    jobs = state.jobs.replace(
        status=jnp.asarray(status), dc=jnp.asarray(dc), jtype=jnp.asarray(jt),
        n=jnp.asarray(n), f_idx=jnp.asarray(f_idx),
        size=jnp.full((J,), 1e9, jnp.float32),
        spu=jnp.asarray(spu), watts=jnp.asarray(watts))
    state = state.replace(jobs=jobs,
                          dc=state.dc.replace(busy=jnp.asarray(busy)))

    idle_floor = float(jnp.sum(
        (eng0.total_gpus - jnp.asarray(busy))
        * jnp.where(eng0.power_gating, eng0.p_sleep, eng0.p_idle)))
    total0 = float(jnp.sum(eng0._dc_power(jobs, jnp.asarray(busy))))
    cap = total0 - cap_drop_w

    params_c = SimParams(**kw, power_cap=cap)
    eng = Engine(fleet, params_c)
    out = jax.jit(eng._cap_greedy)(state)

    ref_jobs = [dict(dc=d, jt=t, n=g, f_idx=fi)
                for _, d, t, g, fi in scenario]
    want = _ref_cap_greedy_model(ref_jobs, fleet, cap, idle_floor)

    got = [int(np.asarray(out.jobs.f_idx)[slot]) for slot, *_ in scenario]
    assert got == want, (cap_drop_w, got, want)
    # cached physics must track the new frequencies
    for (slot, d, t, g, _), fi in zip(scenario, got):
        T, P = eng0._row_TP(jnp.int32(d), jnp.int32(t), jnp.int32(g),
                            jnp.int32(fi))
        np.testing.assert_allclose(float(out.jobs.spu[slot]), float(T),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(out.jobs.watts[slot]), float(P),
                                   rtol=1e-6)
    # the cap is met whenever any headroom remained
    final_total = float(jnp.sum(eng._dc_power(out.jobs, jnp.asarray(busy))))
    min_possible = idle_floor + sum(
        float(eng0._row_TP(jnp.int32(d), jnp.int32(t), jnp.int32(g),
                           jnp.int32(0))[1])
        for _, d, t, g, _ in scenario)
    if cap >= min_possible:
        assert final_total <= cap + 1e-3

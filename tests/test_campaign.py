"""Self-healing chaos-campaign driver (rl/campaign.py).

The acceptance loop of the chaos-native-training tentpole: a gated
campaign segment aborts on a divergence/watchdog trip, rolls the
learner back to the last healthy checkpoint, retries under a reseeded
curriculum, and completes — all recorded in campaign_summary.json.
The full e2e (two chsac training runs) is slow-tier; the gate logic,
divergence probes, and configuration guards are quick.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from distributed_cluster_gpus_tpu.configs.paper import build_duo_fleet
from distributed_cluster_gpus_tpu.fault import ChaosCurriculum
from distributed_cluster_gpus_tpu.models import FaultParams, SimParams
from distributed_cluster_gpus_tpu.obs.health import (DivergenceError,
                                                     RunAbort, WatchdogError)
from distributed_cluster_gpus_tpu.rl.campaign import (
    CampaignConfig, CampaignError, DivergenceConfig, DivergenceMonitor,
    run_campaign)


@pytest.fixture(scope="module")
def duo_fleet():
    return build_duo_fleet()


TINY_CUR = ChaosCurriculum(
    name="tiny", mtbf_lo_s=40.0, mtbf_hi_s=120.0,
    mttr_lo_s=10.0, mttr_hi_s=25.0).sized_for(60.0)

CHSAC_KW = dict(
    algo="chsac_af", duration=60.0, log_interval=5.0,
    inf_mode="poisson", inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
    job_cap=128, queue_cap=256, seed=11, rl_warmup=64, rl_batch=32,
)


def chaos_params(**over):
    kw = dict(CHSAC_KW, faults=FaultParams(curriculum=TINY_CUR),
              obs_enabled=True)
    kw.update(over)
    return SimParams(**kw)


# ---------------------------------------------------------------------------
# divergence probes (quick)
# ---------------------------------------------------------------------------

def test_divergence_monitor_trips():
    m = DivergenceMonitor(DivergenceConfig(critic_loss_max=10.0,
                                           alpha_max=5.0))
    m.check(0, None)  # warmup chunks are a no-op
    m.check(1, {"critic_loss": 1.0, "actor_loss": -2.0, "alpha": 0.5,
                "entropy": 1.2})
    with pytest.raises(DivergenceError, match="non-finite critic_loss"):
        m.check(2, {"critic_loss": float("nan")})
    with pytest.raises(DivergenceError, match="critic_loss"):
        m.check(3, {"critic_loss": 100.0})
    with pytest.raises(DivergenceError, match="alpha"):
        m.check(4, {"critic_loss": 1.0, "alpha": 50.0})
    with pytest.raises(DivergenceError, match="non-finite entropy"):
        m.check(5, {"entropy": np.inf})
    assert m.trips == 4
    # a DivergenceError is a RunAbort (the trainers' flush-and-
    # checkpoint abort path keys on the shared base)
    assert issubclass(DivergenceError, RunAbort)
    assert issubclass(WatchdogError, RunAbort)


def test_campaign_config_validated():
    with pytest.raises(ValueError, match="retries"):
        CampaignConfig(retries=-1)
    with pytest.raises(ValueError, match="backoff"):
        CampaignConfig(backoff_s=-1.0)


def test_campaign_error_carries_structured_context():
    """Automation triages from the exception, not by scraping logs:
    attempt history + the last forensic abort_context path ride the
    error (and default empty/None for hand-raised instances)."""
    attempts = [{"stage": 0, "attempt": 0, "reseed": 0,
                 "outcome": "aborted", "kind": "divergence"}]
    e = CampaignError("budget exhausted", attempts=attempts,
                      abort_context="/runs/ck/stage00_try00/aborted/"
                                    "abort_context.json")
    assert e.attempts == attempts
    assert e.abort_context.endswith("abort_context.json")
    bare = CampaignError("no context")
    assert bare.attempts == [] and bare.abort_context is None


def test_campaign_requires_curriculum(duo_fleet):
    with pytest.raises(ValueError, match="curriculum"):
        run_campaign(duo_fleet, SimParams(**CHSAC_KW))
    with pytest.raises(ValueError, match="curriculum"):
        run_campaign(duo_fleet,
                     SimParams(faults=FaultParams(), **CHSAC_KW))


def test_campaign_refuses_held_out_presets(duo_fleet):
    """Training on a held-out evaluation preset would contaminate the
    held-out chaos scores — the driver must refuse."""
    from distributed_cluster_gpus_tpu.fault import make_chaos_preset

    cur = make_chaos_preset("held_out_stragglers")
    params = chaos_params(faults=FaultParams(curriculum=cur))
    with pytest.raises(ValueError, match="held-out"):
        run_campaign(duo_fleet, params)


# ---------------------------------------------------------------------------
# e2e self-healing loop (slow tier: two chsac training runs)
# ---------------------------------------------------------------------------

class TripOnFirstAttempt(DivergenceMonitor):
    """Deterministic forced divergence: trips once, on the first attempt."""

    def __init__(self):
        super().__init__()
        self.armed = True

    def check(self, chunk, metrics):
        if self.armed and chunk >= 1:
            self.armed = False
            self._trip(chunk, "forced test divergence")


def test_campaign_abort_rollback_reseed_completion(duo_fleet, tmp_path):
    """The acceptance loop: forced divergence -> abort (flushed
    artifacts, aborted summary, forensic checkpoint, chrome trace) ->
    rollback -> reseeded retry -> completion."""
    td = str(tmp_path)
    state, agent, report = run_campaign(
        duo_fleet, chaos_params(), out_dir=td,
        ckpt_dir=os.path.join(td, "ck"), chunk_steps=512,
        config=CampaignConfig(retries=1, backoff_s=0.0),
        monitor=TripOnFirstAttempt())

    assert report["status"] == "completed"
    assert [a["outcome"] for a in report["attempts"]] == \
        ["aborted", "completed"]
    assert report["attempts"][0]["kind"] == "divergence"
    assert report["attempts"][1]["reseed"] == 1, \
        "the retry must re-draw the chaos under a new reseed"
    assert report["retries_used"] == 1

    # the aborted segment flushed its artifacts and stamped the status
    seg0 = os.path.join(td, "stage00_try00")
    rs0 = json.load(open(os.path.join(seg0, "run_summary.json")))
    assert rs0["status"] == "aborted"
    assert os.path.exists(os.path.join(seg0, "abort_trace.json"))
    assert os.path.getsize(os.path.join(seg0, "cluster_log.csv")) > 0
    # forensic checkpoint outside the step_* resume namespace
    ab = os.path.join(td, "ck", "stage00_try00", "aborted")
    assert os.path.isdir(ab)
    assert any(d.startswith("step_") for d in os.listdir(ab))

    # the healed segment completed with a trained agent
    rs1 = json.load(open(
        os.path.join(td, "stage00_try01", "run_summary.json")))
    assert rs1["status"] == "completed"
    assert float(np.asarray(state.t)) >= CHSAC_KW["duration"]
    assert int(agent.sac.step) > 0
    # campaign summary is valid STRICT JSON on disk (no NaN/Infinity
    # tokens) and stamps its schema_version for automation
    with open(os.path.join(td, "campaign_summary.json")) as f:
        doc = json.loads(f.read(), parse_constant=lambda s: pytest.fail(
            f"non-strict JSON token {s} in campaign_summary.json"))
    assert doc["schema"] == "dcg.campaign_summary.v1"
    assert doc["schema_version"] == 1
    assert doc["curriculum"] == "tiny"
    # round-trips bit-exactly through a strict writer
    assert json.loads(json.dumps(doc, allow_nan=False)) == doc


def test_campaign_budget_exhaustion_fails(duo_fleet, tmp_path):
    """Retries run out -> CampaignError, summary status 'failed'."""

    class AlwaysTrip(DivergenceMonitor):
        def check(self, chunk, metrics):
            self._trip(chunk, "forced permanent divergence")

    td = str(tmp_path)
    with pytest.raises(CampaignError, match="budget exhausted") as ei:
        run_campaign(
            duo_fleet, chaos_params(), out_dir=td,
            ckpt_dir=os.path.join(td, "ck"), chunk_steps=512,
            config=CampaignConfig(retries=1, backoff_s=0.0),
            monitor=AlwaysTrip())
    doc = json.load(open(os.path.join(td, "campaign_summary.json")))
    assert doc["status"] == "failed"
    assert len(doc["attempts"]) == 2
    assert all(a["outcome"] == "aborted" for a in doc["attempts"])
    # the error carries the same attempt history + the last forensic
    # abort_context path, replayable as-is
    assert [a["stage"] for a in ei.value.attempts] == \
        [a["stage"] for a in doc["attempts"]]
    assert ei.value.abort_context is not None
    assert os.path.exists(ei.value.abort_context)
    ctx = json.load(open(ei.value.abort_context))
    assert ctx["kind"] == "divergence"


# ---------------------------------------------------------------------------
# rollback fallback on a corrupt store (quick: fixture checkpoints only)
# ---------------------------------------------------------------------------

def test_latest_healthy_skips_corrupt_newest(tmp_path, caplog):
    """The rollback target walk: corrupt the newest checkpoint in a
    fixture store and the campaign degrades to the previous step with a
    logged warning instead of rolling back onto garbage (PR 12: a crash
    mid-save, or bit rot, must not turn one abort into an unrecoverable
    campaign failure)."""
    import logging

    from distributed_cluster_gpus_tpu.rl.campaign import _latest_healthy
    from distributed_cluster_gpus_tpu.utils.checkpoint import (
        save_checkpoint, step_dirname)

    seg0 = str(tmp_path / "stage00_try00")
    trees = {"x": np.arange(6)}
    save_checkpoint(seg0, 1, **trees)
    save_checkpoint(seg0, 3, **trees)
    # the forensic aborted/ namespace stays invisible to the walk
    save_checkpoint(os.path.join(seg0, "aborted"), 9, **trees)
    # bit-rot the newest step's first payload file
    d3 = os.path.join(seg0, step_dirname(3))
    man = json.load(open(os.path.join(d3, "manifest.json")))
    victim = os.path.join(d3, sorted(man["files"])[0])
    with open(victim, "r+b") as f:
        b0 = f.read(1)
        f.seek(0)
        f.write(bytes([b0[0] ^ 0xFF]))

    with caplog.at_level(logging.WARNING, logger="dcg.checkpoint"):
        src, step = _latest_healthy([seg0])
    assert (src, step) == (seg0, 1), \
        "the corrupt newest step must be skipped, not selected"
    assert any("digest mismatch" in r.message for r in caplog.records)

    # a half-written staging dir (crash mid-save) is invisible too
    os.makedirs(os.path.join(seg0, "step_0000000005_tmp"))
    src, step = _latest_healthy([seg0])
    assert (src, step) == (seg0, 1)

    # an entirely-corrupt segment falls back to the previous segment
    seg1 = str(tmp_path / "stage00_try01")
    save_checkpoint(seg1, 0, **trees)
    os.remove(os.path.join(seg1, step_dirname(0), "COMMIT"))
    src, step = _latest_healthy([seg0, seg1])
    assert (src, step) == (seg0, 1)

"""Step-time attribution: the phase partition's 100%-coverage contract.

The partition is built from cumulative-prefix traces of the engine's
``attrib_stop`` ablation knob, so three properties make it trustworthy:

* prefixes NEST — every phase delta is nonnegative;
* coverage is total — phase eqns sum exactly to the full step body's
  flattened count (no unattributed residue);
* the full count equals the PINNED ceiling's measured eqns
  (analysis/baselines.json), i.e. the ablation knob does not perturb
  the production program.

The quick tier pins the three structural families (singleton planner,
superstep, fault superstep); the slow tier sweeps every canonical lint
config.  The compiled-measurement path (attribute_config without
trace_only) is exercised at a tiny shape in the slow tier — wall-clock
ASSERTIONS stay structural (a timing inequality would flake in CI).
"""

import json

import jax
import pytest

from distributed_cluster_gpus_tpu.analysis import attrib, lint
from distributed_cluster_gpus_tpu.configs import build_fleet
from distributed_cluster_gpus_tpu.sim.engine import init_state


@pytest.fixture(scope="module")
def fleet():
    return build_fleet()


@pytest.fixture(scope="module")
def baselines():
    return lint.load_baselines()


QUICK_CONFIGS = ["joint_nf/ring/K1", "joint_nf/ring/K4"]
SLOW_CONFIGS = [c.name for c in lint.canonical_configs()
                if c.name not in QUICK_CONFIGS]


def _partition(fleet, name):
    spec = lint.config_by_name(name)
    eng, pp = lint.build_engine(fleet, spec)
    st = init_state(jax.random.key(0), fleet, eng.params,
                    workload=eng.workload)
    return eng, attrib.phase_partition(eng, st, pp)


def _check_partition(fleet, baselines, name):
    eng, part = _partition(fleet, name)
    phases = part["phases"]
    assert all(ph["eqns"] >= 0 for ph in phases), phases
    assert sum(ph["eqns"] for ph in phases) == part["eqns_total"]
    # the ablation knob must not perturb the production program: the
    # full-prefix count IS the pinned ceiling's measured eqn count
    assert part["eqns_total"] == lint.measured_for(name, baselines), (
        f"{name}: attribution full trace disagrees with the banked "
        "baseline — attrib_stop leaked into the attrib_stop=None program "
        "or baselines are stale (scripts/lint_graph.py "
        "--update-baselines)")
    labels = [ph["phase"] for ph in phases]
    assert len(labels) == len(set(labels)), f"duplicate phases: {labels}"
    assert labels[0] == "event_min_head"
    if eng.superstep_on:
        assert "selection_payload" in labels
    else:
        assert "event_switch_payloads" in labels
    if eng.planner_on:
        assert "commit_plan" in labels
    assert labels[-1] == ("obs_block" if eng.obs_on else "finalize")


@pytest.mark.parametrize("name", QUICK_CONFIGS)
def test_partition_covers_step_and_matches_pinned_ceiling(
        fleet, baselines, name):
    _check_partition(fleet, baselines, name)


@pytest.mark.slow
@pytest.mark.parametrize("name", SLOW_CONFIGS)
def test_partition_full_canonical_matrix(fleet, baselines, name):
    _check_partition(fleet, baselines, name)


@pytest.mark.slow
def test_rl_partition_has_policy_tail(fleet, baselines):
    eng, part = _partition(fleet, "chsac_af/ring/K1")
    labels = [ph["phase"] for ph in part["phases"]]
    assert "policy_tail" in labels
    tail = next(ph for ph in part["phases"]
                if ph["phase"] == "policy_tail")
    # the policy tail is the RL step's known heavyweight — if it drops
    # to a sliver the stop moved and the partition is mislabeled
    assert tail["eqn_share"] > 0.2, part["phases"]
    assert part["eqns_total"] == lint.measured_for(
        "chsac_af/ring/K1", baselines)


def test_attrib_cli_trace_only_emits_lint_report_shape(fleet, tmp_path):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "attrib_step", os.path.join(os.path.dirname(__file__), "..",
                                    "scripts", "attrib_step.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = tmp_path / "attrib.json"
    rc = mod.main(["--trace-only", "--config", "joint_nf/ring/K1",
                   "--json", str(out)])
    assert rc == 0
    rep = json.loads(out.read_text())
    assert rep["schema"] == "dcg.lint_report.v1"
    assert rep["tool"] == "attrib_step"
    assert rep["ok"] and rep["checked"] == ["joint_nf/ring/K1"]
    (doc,) = rep["attrib"]
    assert doc["schema"] == "dcg.phase_attrib.v1"
    assert "measured" not in doc  # trace-only skips the compile arms
    assert sum(ph["eqns"] for ph in doc["phases"]) == doc["eqns_total"]
    for ph in doc["phases"]:
        assert ph["predicted_time_share"] == ph["eqn_share"]


@pytest.mark.slow
def test_measured_attribution_tiny_shape(fleet):
    """The compiled measurement path end to end at a tiny shape: every
    phase carries ms_per_step, the whole-step time is positive, and the
    report names a top phase.  No timing inequalities — CI boxes are
    noisy; the 10%-sum acceptance gate is exercised by the CLI run the
    driver banks (BENCH_ATTRIB)."""
    rep = attrib.attribute_config(
        fleet, "joint_nf/ring/K1", n_rollouts=2, chunk_steps=32,
        warm_chunks=1, timed_chunks=1, reps=3)
    m = rep["measured"]
    assert m["whole_step_ms"] > 0
    assert all("ms_per_step" in ph for ph in rep["phases"])
    assert rep["top_phase"]["phase"] in {ph["phase"]
                                         for ph in rep["phases"]}
    assert m["sum_vs_whole"] is not None

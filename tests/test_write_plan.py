"""Write-plan commit (round 9): planner programs realize bit-identical runs.

The engine's planner path (`Engine.planner_on`) rebuilds the event switch
as pure planners + one shared commit (`_commit_plan`; chsac adds
`_commit_tail`).  The legacy round-8 program is still compiled for the
statically ineligible configurations (bandit / chsac+elastic / faults),
which makes it available as a GOLDEN: forcing ``planner_on = False`` on an
otherwise planner-eligible config traces the old in-branch write chains,
and the two programs must produce the SAME run — every SimState leaf,
every emission, and (for the io-level tests) byte-identical CSVs and
metrics.jsonl.

These are the round-9 equivalents of the superstep's K-vs-1 goldens: the
plan relocates writes, it must never change a value.
"""

import filecmp

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state


def _mismatches(a, b):
    bad = []

    def eq(path, x, y):
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        if not np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True):
            bad.append(jax.tree_util.keystr(path))

    jax.tree_util.tree_map_with_path(eq, a, b)
    return bad


def _run_pair(fleet, algo, queue_mode, policy=None, pp=None, n_steps=1024,
              **kw):
    """(planner state+emissions, legacy state+emissions) for one config."""
    params = SimParams(algo=algo, queue_mode=queue_mode, **kw)
    outs = []
    for planner in (True, False):
        eng = Engine(fleet, params, policy_apply=policy)
        assert eng.planner_on, "config unexpectedly planner-ineligible"
        if not planner:
            eng.planner_on = False  # compile the round-8 golden program
        st = init_state(jax.random.key(0), fleet, params)
        outs.append(eng._run_chunk(st, pp, n_steps))
    return outs


RUN_KW = dict(duration=600.0, log_interval=5.0, inf_mode="sinusoid",
              inf_rate=2.0, trn_mode="poisson", trn_rate=0.1, job_cap=64,
              lat_window=128, seed=3, queue_cap=128)


@pytest.mark.parametrize("algo,queue_mode", [
    ("joint_nf", "ring"),
    ("default_policy", "slab"),
    ("eco_route", "ring"),
    ("carbon_cost", "slab"),
    ("debug", "ring"),
])
def test_planner_bit_identical(fleet, algo, queue_mode):
    (s1, e1), (s0, e0) = _run_pair(fleet, algo, queue_mode, **RUN_KW)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"planner diverged from legacy in: {bad}"
    assert int(s1.n_finished.sum()) > 50  # the golden actually did work


def test_planner_bit_identical_cap_controller(fleet):
    """The cap controllers keep their in-branch whole-array clamps (the
    log branch is not a row plan); the planner relocation around them
    must still be exact."""
    kw = dict(RUN_KW, power_cap=20000.0)
    (s1, e1), (s0, e0) = _run_pair(fleet, "cap_greedy", "ring", **kw)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"cap_greedy planner diverged: {bad}"


def test_planner_bit_identical_degenerate_pressure(fleet):
    """Tiny slab: arrivals spill to the rings, drops occur, and the
    post-switch drain fires constantly — the plan's evict/spill paths and
    the merged masked drain are all live, and must still be exact."""
    # ring drops on ring-full (needs a tiny queue_cap); slab drops on
    # slab-full (job_cap alone) — size each leg so its drop path fires
    for qm, qcap in (("ring", 16), ("slab", 512)):
        kw = dict(RUN_KW, job_cap=8, queue_cap=qcap, inf_rate=4.0,
                  log_interval=2.0, duration=120.0)
        (s1, e1), (s0, e0) = _run_pair(fleet, "default_policy", qm,
                                       n_steps=4096, **kw)
        bad = _mismatches(s1, s0) + _mismatches(e1, e0)
        assert not bad, f"degenerate {qm} planner diverged: {bad}"
        assert int(s1.n_dropped) > 0 and int(s1.n_finished.sum()) > 50


def _chsac_setup(fleet):
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)

    params = SimParams(algo="chsac_af", **RUN_KW)
    cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
                    n_g=params.max_gpus_per_job,
                    constraints=default_constraints(500.0))
    return make_policy_apply(cfg), sac_init(cfg, jax.random.key(1))


@pytest.mark.parametrize("queue_mode", ["ring", "slab"])
def test_planner_bit_identical_chsac(fleet, queue_mode):
    """chsac: the policy tail's route/materialize/start writes ride
    `_commit_tail` — transitions, emissions, and every state leaf must
    match the legacy dispatch exactly (the RL stream feeds training, so
    a single differing bit would silently change trajectories)."""
    policy, sac = _chsac_setup(fleet)
    (s1, e1), (s0, e0) = _run_pair(fleet, "chsac_af", queue_mode,
                                   policy=policy, pp=sac, **RUN_KW)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"chsac {queue_mode} planner diverged: {bad}"
    assert int(np.asarray(e1["rl"]["valid"]).sum()) > 50


def _force_legacy(monkeypatch):
    """Make every Engine built inside run_simulation compile the legacy
    (round-8) program."""
    orig = Engine.__init__

    def patched(self, *a, **kw):
        orig(self, *a, **kw)
        self.planner_on = False

    monkeypatch.setattr(Engine, "__init__", patched)


def test_planner_csv_and_metrics_bytes_unchanged(fleet, tmp_path,
                                                 monkeypatch):
    """io-level golden, obs-on: cluster/job CSVs AND the obs exporters'
    metrics.jsonl are byte-identical between the planner and legacy
    programs (the telemetry fold runs after the commit, so obs rows see
    the same closed step either way)."""
    from distributed_cluster_gpus_tpu.obs.export import ObsConfig
    from distributed_cluster_gpus_tpu.sim.io import run_simulation

    params = SimParams(algo="joint_nf", queue_mode="ring", obs_enabled=True,
                       **dict(RUN_KW, duration=120.0))
    out = {}
    for mode in ("planner", "legacy"):
        d = str(tmp_path / mode)
        with pytest.MonkeyPatch.context() as mp:
            if mode == "legacy":
                _force_legacy(mp)
            run_simulation(fleet, params, out_dir=d, chunk_steps=2048,
                           obs=ObsConfig(out_dir=d, watchdog="warn"))
        out[mode] = d
    for name in ("cluster_log.csv", "job_log.csv", "metrics.jsonl"):
        assert filecmp.cmp(f"{out['planner']}/{name}",
                           f"{out['legacy']}/{name}", shallow=False), (
            f"{name} bytes differ between planner and legacy programs")


def test_planner_static_gate():
    """The planner compile gate: bandit, chsac+elastic, and fault runs
    keep the legacy program; everything else plans."""
    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.configs.paper import (
        build_incident_faults)

    fleet = build_fleet()
    base = dict(duration=60.0, log_interval=5.0, inf_mode="poisson",
                inf_rate=2.0, trn_mode="off", job_cap=64, lat_window=64,
                seed=0)
    assert Engine(fleet, SimParams(algo="default_policy", **base)).planner_on
    assert Engine(fleet, SimParams(algo="joint_nf", **base)).planner_on
    assert not Engine(fleet, SimParams(algo="bandit", **base)).planner_on
    assert not Engine(
        fleet, SimParams(algo="default_policy",
                         faults=build_incident_faults(10.0, 20.0),
                         **base)).planner_on
    # chsac+elastic needs a policy callable to construct; check the flag
    # through the params combination the gate reads
    p = SimParams(algo="chsac_af", elastic_scaling=True, **base)
    eng = Engine(fleet, p, policy_apply=lambda *a: (0, 0))
    assert not eng.planner_on

"""Write-plan commit (rounds 9 + 12): planner programs realize
bit-identical runs.

The engine's planner path (`Engine.planner_on`) rebuilds the event switch
as pure planners + one shared commit (`_commit_plan`; chsac adds
`_commit_tail`).  Since round 12 EVERY configuration plans — the round-9
holdouts (bandit / chsac+elastic / faults) landed their own planner
paths, and the xfer admission rides iteration 0 of the shared masked
drain on fault-free programs — so the legacy round-8 program exists ONLY
as a forced golden: forcing ``planner_on = False`` traces the old
in-branch write chains, and the two programs must produce the SAME run —
every SimState leaf, every emission, and (for the io-level tests)
byte-identical CSVs and metrics.jsonl.

These are the round-9/12 equivalents of the superstep's K-vs-1 goldens:
the plan relocates writes, it must never change a value.
"""

import filecmp

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state


def _mismatches(a, b):
    bad = []

    def eq(path, x, y):
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        if not np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True):
            bad.append(jax.tree_util.keystr(path))

    jax.tree_util.tree_map_with_path(eq, a, b)
    return bad


def _run_pair(fleet, algo, queue_mode, policy=None, pp=None, n_steps=1024,
              **kw):
    """(planner state+emissions, legacy state+emissions) for one config."""
    params = SimParams(algo=algo, queue_mode=queue_mode, **kw)
    outs = []
    for planner in (True, False):
        eng = Engine(fleet, params, policy_apply=policy)
        assert eng.planner_on, "config unexpectedly planner-ineligible"
        if not planner:
            eng.planner_on = False  # compile the round-8 golden program
        st = init_state(jax.random.key(0), fleet, params)
        outs.append(eng._run_chunk(st, pp, n_steps))
    return outs


RUN_KW = dict(duration=600.0, log_interval=5.0, inf_mode="sinusoid",
              inf_rate=2.0, trn_mode="poisson", trn_rate=0.1, job_cap=64,
              lat_window=128, seed=3, queue_cap=128)


@pytest.mark.parametrize("algo,queue_mode", [
    ("joint_nf", "ring"),
    ("default_policy", "slab"),
    ("eco_route", "ring"),
    ("carbon_cost", "slab"),
    ("debug", "ring"),
])
def test_planner_bit_identical(fleet, algo, queue_mode):
    (s1, e1), (s0, e0) = _run_pair(fleet, algo, queue_mode, **RUN_KW)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"planner diverged from legacy in: {bad}"
    assert int(s1.n_finished.sum()) > 50  # the golden actually did work


def test_planner_bit_identical_cap_controller(fleet):
    """The cap controllers keep their in-branch whole-array clamps (the
    log branch is not a row plan); the planner relocation around them
    must still be exact."""
    kw = dict(RUN_KW, power_cap=20000.0)
    (s1, e1), (s0, e0) = _run_pair(fleet, "cap_greedy", "ring", **kw)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"cap_greedy planner diverged: {bad}"


def test_planner_bit_identical_degenerate_pressure(fleet):
    """Tiny slab: arrivals spill to the rings, drops occur, and the
    post-switch drain fires constantly — the plan's evict/spill paths and
    the merged masked drain are all live, and must still be exact."""
    # ring drops on ring-full (needs a tiny queue_cap); slab drops on
    # slab-full (job_cap alone) — size each leg so its drop path fires
    for qm, qcap in (("ring", 16), ("slab", 512)):
        kw = dict(RUN_KW, job_cap=8, queue_cap=qcap, inf_rate=4.0,
                  log_interval=2.0, duration=120.0)
        (s1, e1), (s0, e0) = _run_pair(fleet, "default_policy", qm,
                                       n_steps=4096, **kw)
        bad = _mismatches(s1, s0) + _mismatches(e1, e0)
        assert not bad, f"degenerate {qm} planner diverged: {bad}"
        assert int(s1.n_dropped) > 0 and int(s1.n_finished.sum()) > 50


def _chsac_setup(fleet, **kw):
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)

    params = SimParams(algo="chsac_af", **{**RUN_KW, **kw})
    cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
                    n_g=params.max_gpus_per_job,
                    constraints=default_constraints(500.0))
    return make_policy_apply(cfg), sac_init(cfg, jax.random.key(1))


@pytest.mark.parametrize("queue_mode", ["ring", "slab"])
def test_planner_bit_identical_chsac(fleet, queue_mode):
    """chsac: the policy tail's route/materialize/start writes ride
    `_commit_tail` — transitions, emissions, and every state leaf must
    match the legacy dispatch exactly (the RL stream feeds training, so
    a single differing bit would silently change trajectories)."""
    policy, sac = _chsac_setup(fleet)
    (s1, e1), (s0, e0) = _run_pair(fleet, "chsac_af", queue_mode,
                                   policy=policy, pp=sac, **RUN_KW)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"chsac {queue_mode} planner diverged: {bad}"
    assert int(np.asarray(e1["rl"]["valid"]).sum()) > 50


def _force_legacy(monkeypatch):
    """Make every Engine built inside run_simulation compile the legacy
    (round-8) program."""
    orig = Engine.__init__

    def patched(self, *a, **kw):
        orig(self, *a, **kw)
        self.planner_on = False

    monkeypatch.setattr(Engine, "__init__", patched)


def test_planner_csv_and_metrics_bytes_unchanged(fleet, tmp_path,
                                                 monkeypatch):
    """io-level golden, obs-on: cluster/job CSVs AND the obs exporters'
    metrics.jsonl are byte-identical between the planner and legacy
    programs (the telemetry fold runs after the commit, so obs rows see
    the same closed step either way)."""
    from distributed_cluster_gpus_tpu.obs.export import ObsConfig
    from distributed_cluster_gpus_tpu.sim.io import run_simulation

    params = SimParams(algo="joint_nf", queue_mode="ring", obs_enabled=True,
                       **dict(RUN_KW, duration=120.0))
    out = {}
    for mode in ("planner", "legacy"):
        d = str(tmp_path / mode)
        with pytest.MonkeyPatch.context() as mp:
            if mode == "legacy":
                _force_legacy(mp)
            run_simulation(fleet, params, out_dir=d, chunk_steps=2048,
                           obs=ObsConfig(out_dir=d, watchdog="warn"))
        out[mode] = d
    for name in ("cluster_log.csv", "job_log.csv", "metrics.jsonl"):
        assert filecmp.cmp(f"{out['planner']}/{name}",
                           f"{out['legacy']}/{name}", shallow=False), (
            f"{name} bytes differ between planner and legacy programs")


def test_planner_static_gate():
    """Round 12: the planner gate is UNIVERSAL — the round-9 holdouts
    (bandit / chsac+elastic / faults) plan too, and the static
    planner-ineligibility residue is pinned EMPTY."""
    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.configs.paper import (
        build_incident_faults)
    from distributed_cluster_gpus_tpu.sim.engine import static_ineligibility

    fleet = build_fleet()
    base = dict(duration=60.0, log_interval=5.0, inf_mode="poisson",
                inf_rate=2.0, trn_mode="off", job_cap=64, lat_window=64,
                seed=0)
    assert Engine(fleet, SimParams(algo="default_policy", **base)).planner_on
    assert Engine(fleet, SimParams(algo="joint_nf", **base)).planner_on
    assert Engine(fleet, SimParams(algo="bandit", **base)).planner_on
    faulted = SimParams(algo="default_policy",
                        faults=build_incident_faults(10.0, 20.0), **base)
    assert Engine(fleet, faulted).planner_on
    # chsac+elastic needs a policy callable to construct; check the flag
    # through the params combination the gate reads
    p = SimParams(algo="chsac_af", elastic_scaling=True, **base)
    eng = Engine(fleet, p, policy_apply=lambda *a: (0, 0))
    assert eng.planner_on
    for params in (p, faulted, SimParams(algo="bandit", **base)):
        assert static_ineligibility(params)["planner"] == [], (
            "the planner ineligibility residue regrew — round 12 pinned "
            "it empty")


@pytest.mark.parametrize("queue_mode", ["ring", "slab"])
def test_planner_bit_identical_bandit(fleet, queue_mode):
    """Round 12: bandit plans — the finish branch's reward update rides
    the plan's ``bandit`` carry and the per-start UCB select runs
    predicated inside the shared masked drain (xfer admissions via its
    iteration-0 direct path).  The arm statistics thread event-to-event
    in the legacy order, so every pull count, reward sum, and chosen
    frequency must match the legacy program bit-for-bit."""
    (s1, e1), (s0, e0) = _run_pair(fleet, "bandit", queue_mode, **RUN_KW)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"bandit {queue_mode} planner diverged: {bad}"
    assert int(np.asarray(s1.bandit.t)) > 50  # the arms were really pulled


def _dense_chaos():
    """An early, busy fault schedule: outages sweep six DCs while work is
    live, plus derate and WAN windows — so the goldens exercise real
    preemptions, migrations, clamps, and degraded transfers (the
    anti-vacuity asserts pin that they fired)."""
    from distributed_cluster_gpus_tpu.models import FaultParams

    return FaultParams(
        outages=tuple((d, 4.0 + 2.0 * d, 14.0 + 2.0 * d) for d in range(6)),
        derates=((1, 3.0, 20.0, 0.6), (3, 6.0, 25.0, 0.6)),
        wan=((0, 2, 2.0, 25.0, 3.0, 0.1),))


@pytest.mark.parametrize("queue_mode", ["ring", "slab"])
def test_planner_bit_identical_faults(fleet, queue_mode):
    """Round 12: fault runs plan — the EV_FAULT branch keeps its
    whole-array masked writes in-branch (like the log tick) while the
    row events plan; outage preemption/migration, straggler-derate
    start clamps, WAN-degraded transfers, and the recovery drains (slab
    before the migration sweep, ring after — the legacy order) must all
    reproduce the legacy program bit-for-bit."""
    kw = dict(RUN_KW, trn_rate=1.0, faults=_dense_chaos())
    (s1, e1), (s0, e0) = _run_pair(fleet, "default_policy", queue_mode,
                                   **kw)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"faulted {queue_mode} planner diverged: {bad}"
    assert int(np.asarray(s1.fault.n_preempted)) > 0  # chaos was real
    assert int(np.asarray(s1.fault.n_migrated)) > 0


@pytest.mark.parametrize("queue_mode", ["ring", "slab"])
def test_planner_bit_identical_bandit_faults(fleet, queue_mode):
    """Round 12 (review catch): bandit + faults COMPOSE — the fault
    program keeps the xfer start in `_plan_xfer`, so its admission must
    dispatch through `bandit_select` (the legacy `_decide_nf` arm) with
    the pull-count update riding the plan's bandit carry, committed
    only when the start fires.  The first cut fell through to the
    heuristic path there and diverged on 43 leaves; arm statistics AND
    fault counters must reproduce the legacy program bit-for-bit."""
    kw = dict(RUN_KW, trn_rate=1.0, faults=_dense_chaos())
    (s1, e1), (s0, e0) = _run_pair(fleet, "bandit", queue_mode, **kw)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"bandit+faults {queue_mode} planner diverged: {bad}"
    assert int(np.asarray(s1.bandit.t)) > 50  # the arms were really pulled
    assert int(np.asarray(s1.fault.n_preempted)) > 0  # chaos was real


def test_planner_bit_identical_chsac_elastic(fleet):
    """Round 12: chsac+elastic plans — the finish branch's reallocation
    sweep relocates to right after the shared commit (identical
    position, key derivation, and post-retire state), so preemption
    counters, re-placement actions, and the RL stream must match the
    legacy dispatch exactly.  Three hand-placed long training jobs
    guarantee the first training finish fires a real reallocation
    (organic draws rarely overlap training jobs long enough)."""
    from distributed_cluster_gpus_tpu.models import JobStatus

    policy, sac = _chsac_setup(fleet, elastic_scaling=True)
    params = SimParams(algo="chsac_af", queue_mode="ring",
                       elastic_scaling=True, **RUN_KW)
    outs = []
    for planner in (True, False):
        eng = Engine(fleet, params, policy_apply=policy)
        assert eng.planner_on
        if not planner:
            eng.planner_on = False
        st = init_state(jax.random.key(0), fleet, params)
        jobs = st.jobs
        for j, size in enumerate([100.0, 5000.0, 6000.0]):
            f_idx = int(st.dc.cur_f_idx[0])
            spu, watts = eng._row_TP(jnp.int32(0), jnp.int32(1),
                                     jnp.int32(2), jnp.int32(f_idx))
            jobs = jobs.replace(
                status=jobs.status.at[j].set(JobStatus.RUNNING),
                jtype=jobs.jtype.at[j].set(1),
                seq=jobs.seq.at[j].set(j + 1),
                size=jobs.size.at[j].set(size),
                n=jobs.n.at[j].set(2),
                f_idx=jobs.f_idx.at[j].set(f_idx),
                spu=jobs.spu.at[j].set(spu),
                watts=jobs.watts.at[j].set(watts),
                t_start=jobs.t_start.at[j].set(0.001),
            )
        st = st.replace(jobs=jobs, jid_counter=jnp.int32(4),
                        dc=st.dc.replace(busy=st.dc.busy.at[0].set(6)))
        outs.append(eng._run_chunk(st, sac, 1024))
    (s1, e1), (s0, e0) = outs
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"chsac+elastic planner diverged: {bad}"
    # the reallocation really fired: the hand-placed long jobs carry
    # preemption counts (still in the slab or finished through the log)
    pc = int(np.asarray(s1.jobs.preempt_count).sum()) + int(
        np.asarray(e1["job"])[:, 11].sum())
    assert pc > 0, "elastic reallocation never fired — vacuous golden"


def test_planner_bit_identical_chsac_faults(fleet):
    """Round 12: chsac under chaos plans — the headline campaign shape
    (policy tail + EV_FAULT windows + WAN-degraded routing + derate
    clamps through `_commit_tail`) byte-compared against the legacy
    program."""
    policy, sac = _chsac_setup(fleet, trn_rate=1.0)
    kw = dict(RUN_KW, trn_rate=1.0, faults=_dense_chaos())
    (s1, e1), (s0, e0) = _run_pair(fleet, "chsac_af", "ring",
                                   policy=policy, pp=sac, **kw)
    bad = _mismatches(s1, s0) + _mismatches(e1, e0)
    assert not bad, f"chsac+faults planner diverged: {bad}"
    assert int(np.asarray(e1["rl"]["valid"]).sum()) > 50
    assert int(np.asarray(s1.fault.n_preempted)) > 0

"""twin/ — resident digital-twin serving mode (round 19).

The correctness anchors, in dependency order:

* **ingest** — a twin fed the trace in 3 segments lands on a warm state
  BIT-IDENTICAL to one batch run over the concatenated trace (the
  speculative-chunk acceptance rule `arr_count <= n_valid` is exactly
  the soundness frontier), and a SIGKILLed twin resumes from its last
  verified chunk to the same bytes;
* **fork** — a forecast never mutates the warm state (quick tier), is
  byte-deterministic across repeats, and at t0=0 every lane row equals
  the serial ``run_algo`` row for the overlayed params (the golden that
  pins `_reinit_streams` to `init_clocks` draw #0);
* **satellites** — the `--append` validator CLI, the fsck twin-store
  recognition, the windowed `copy_store_window`/`replay_run steps=`,
  the RCA window reproducing history, and the ledger's ``twin_latency``
  record kind.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from conftest import tree_mismatches  # noqa: E402

from distributed_cluster_gpus_tpu.configs import build_duo_fleet  # noqa: E402
from distributed_cluster_gpus_tpu.models import SimParams  # noqa: E402
from distributed_cluster_gpus_tpu.twin import (  # noqa: E402
    Overlay, TraceCursor, Twin, TwinService, forecast)

CHUNK = 256


@pytest.fixture(scope="module")
def duo():
    return build_duo_fleet()


def _times(n=600, rate=5.0, seed=11):
    rng = np.random.default_rng(seed)
    return np.round(np.cumsum(rng.exponential(1.0 / rate, n)), 6)


def _doc(times, signals=False):
    doc = {"name": "twin_test",
           "streams": {"inference": {"kind": "trace",
                                     "times": np.asarray(times).tolist()},
                       "training": {"kind": "off"}}}
    if signals:
        doc["signals"] = {"price": [0.1] * 24, "carbon": [420.0, 310.0],
                          "bin_s": 300.0, "periodic": True}
    return doc


def _seg(times):
    return {"streams": {"inference": {"kind": "trace",
                                      "times": np.asarray(times).tolist()},
                        "training": {"kind": "off"}}}


def _params(times, algo="default_policy"):
    return SimParams(algo=algo, duration=float(times[-1]) + 5.0, seed=0)


# ---------------------------------------------------------------------------
# cursor validation: every rejection class, host-side only (no compiles)
# ---------------------------------------------------------------------------

def test_cursor_rejects_bad_segments(duo):
    t = _times(50)
    cur = TraceCursor(duo, _doc(t))
    last = float(t[-1])

    fails = cur.validate_segment(_seg([last - 1.0, last + 1.0]))
    assert any("precedes the base trace's last" in f for f in fails)

    fails = cur.validate_segment(_seg([last + 2.0, last + 1.0]))
    assert any("non-decreasing" in f for f in fails)

    seg = _seg([last + 1.0])
    seg["signals"] = {"price": [1.0], "bin_s": 60.0}
    fails = cur.validate_segment(seg)
    assert any("must not carry signals" in f for f in fails)

    seg = {"streams": {"inference": {"kind": "poisson", "rate": 1.0},
                       "training": {"kind": "off"}}}
    fails = cur.validate_segment(seg)
    assert any("may only append trace events" in f for f in fails)

    # training base stream is 'off', not a trace: nothing to append to
    seg = {"streams": {"inference": {"kind": "off"},
                       "training": {"kind": "trace",
                                    "times": [last + 1.0]}}}
    fails = cur.validate_segment(seg)
    assert any("not a trace" in f for f in fails)

    # sizes on a sizeless base trace
    seg = _seg([last + 1.0])
    seg["streams"]["inference"]["sizes"] = [2.0]
    fails = cur.validate_segment(seg)
    assert any("size column mismatch" in f for f in fails)

    # a rejecting validate leaves the cursor untouched
    assert cur.segments == 1 and cur.n_valid()[0] == 50


def test_cursor_append_advances_watermark(duo):
    t = _times(60)
    cur = TraceCursor(duo, _doc(t[:30]))
    fp0 = cur.fingerprint()
    assert cur.watermark_t() == pytest.approx(float(t[29]))
    assert cur.append(_seg(t[30:])) == []
    assert cur.segments == 2
    assert cur.n_valid() == {0: 60, 2: 60}
    assert cur.watermark_t() == pytest.approx(float(t[-1]))
    assert cur.fingerprint() != fp0
    cur.close()
    assert cur.watermark_t() == float("inf")
    assert cur.append(_seg([float(t[-1]) + 1.0]))  # closed: rejected
    spec = cur.concatenated_spec()
    assert spec.name.endswith("+2seg")
    np.testing.assert_array_equal(spec.streams[0][0].times, t)


def test_twin_guards(duo):
    t = _times(50)
    with pytest.raises(ValueError, match="cannot run algo"):
        Twin(duo, _params(t, algo="chsac_af"), TraceCursor(duo, _doc(t)))
    empty = {"streams": {"inference": {"kind": "trace", "times": []},
                         "training": {"kind": "off"}}}
    with pytest.raises(ValueError, match="is empty"):
        Twin(duo, _params(t), TraceCursor(duo, empty))


# ---------------------------------------------------------------------------
# ingest: 3 segments == batch, bit for bit (the tentpole acceptance)
# ---------------------------------------------------------------------------

def test_incremental_matches_batch(duo):
    t = _times(900)
    params = _params(t)

    cur = TraceCursor(duo, _doc(t[:300]))
    twin = Twin(duo, params, cur, chunk_steps=CHUNK)
    twin.advance()
    assert not twin.done  # the open frontier must hold it back
    for lo, hi in ((300, 600), (600, 900)):
        assert cur.append(_seg(t[lo:hi])) == []
        twin.advance()
    cur.close()
    twin.advance()
    assert twin.done

    cur_b = TraceCursor(duo, _doc(t))
    cur_b.close()
    batch = Twin(duo, params, cur_b, chunk_steps=CHUNK)
    batch.advance()
    assert batch.done
    assert tree_mismatches(twin.state, batch.state) == []
    assert twin.chunk == batch.chunk


def test_ingest_lag_and_watermark_doc(duo):
    t = _times(400)
    cur = TraceCursor(duo, _doc(t))
    twin = Twin(duo, _params(t), cur, chunk_steps=CHUNK)
    twin.advance(max_chunks=2)
    lag = twin.ingest_lag_s()
    assert 0.0 < lag <= float(t[-1])
    doc = twin.watermark_doc()
    assert doc["chunk"] == 2 and not doc["closed"]
    assert doc["ingest_lag_s"] == pytest.approx(lag)
    assert doc["n_valid"] == {"0": 400, "2": 400}
    json.dumps(doc)  # strict-JSON-able


# ---------------------------------------------------------------------------
# fork: purity (quick tier), determinism, and the t0=0 golden
# ---------------------------------------------------------------------------

def test_fork_never_mutates_warm_state(duo):
    t = _times(300)
    twin = Twin(duo, _params(t), TraceCursor(duo, _doc(t, signals=True)),
                chunk_steps=CHUNK)
    twin.advance(max_chunks=2)
    before = twin.state
    r1 = forecast(twin, ["eco_route"], [Overlay(kind="price_spike")],
                  horizon_s=20.0, chunk_steps=CHUNK)
    assert twin.state is before or tree_mismatches(twin.state, before) == []
    assert twin.chunk == 2
    r2 = forecast(twin, ["eco_route"], [Overlay(kind="price_spike")],
                  horizon_s=20.0, chunk_steps=CHUNK)
    assert (json.dumps(r1, sort_keys=True, default=float)
            == json.dumps(r2, sort_keys=True, default=float))
    assert len(r1["lanes"]) == 2  # baseline lane prepended
    base = r1["lanes"][0]
    assert base["policy"] == "default_policy" and base["overlay"] == "none"
    assert all(v == 0 for v in base["delta"].values())  # delta vs itself


def test_forecast_golden_t0_zero(duo):
    """Every vmapped lane at t0=0 equals the serial run_algo row for the
    overlayed params — 2 policies x 2 overlays plus the baseline."""
    import dataclasses

    from distributed_cluster_gpus_tpu.evaluation import run_algo
    from distributed_cluster_gpus_tpu.twin.fork import (
        overlay_faults, overlay_spec)

    t = _times(400, seed=3)
    doc = _doc(t, signals=True)
    cursor = TraceCursor(duo, doc)
    params = SimParams(algo="default_policy", duration=120.0, seed=0)
    twin = Twin(duo, params, cursor, chunk_steps=CHUNK)  # NOT advanced

    ovs = (Overlay(kind="price_spike"), Overlay(kind="blackout"))
    res = forecast(twin, ("default_policy", "eco_route"), ovs,
                   horizon_s=60.0, chunk_steps=CHUNK)
    assert len(res["lanes"]) == 5
    by_name = {o.name: o for o in ovs + (Overlay(),)}
    for ln in res["lanes"]:
        ov = by_name[ln["overlay"]]
        p = dataclasses.replace(
            twin.params, algo=ln["policy"], duration=60.0,
            workload=overlay_spec(cursor.spec, duo, ov, 0.0, 60.0),
            faults=overlay_faults(twin.params.faults, ov, 60.0))
        row = run_algo(duo, p, chunk_steps=CHUNK).row()
        assert (json.dumps(ln["row"], sort_keys=True, default=float)
                == json.dumps(row, sort_keys=True, default=float)), \
            f"lane {ln['policy']}/{ln['overlay']} diverges from run_algo"


def test_overlay_from_dict_rejects_unknown_keys():
    with pytest.raises(ValueError):
        Overlay.from_dict({"kind": "price_spike", "factr": 2.0})
    with pytest.raises(ValueError):
        Overlay(kind="sharknado")
    ov = Overlay.from_dict({"kind": "blackout", "stage": 1})
    assert ov.name == "held_out_regional_blackout"


# ---------------------------------------------------------------------------
# crash-resume: SIGKILL mid-ingest, resumed bytes identical (subprocess)
# ---------------------------------------------------------------------------

_KILL_CHILD = r'''
import sys
import numpy as np
sys.path.insert(0, {here!r})
from distributed_cluster_gpus_tpu.configs import build_duo_fleet
from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.twin import TraceCursor, Twin

rng = np.random.default_rng(11)
times = np.round(np.cumsum(rng.exponential(0.2, 600)), 6)
doc = {{"name": "twin_test",
        "streams": {{"inference": {{"kind": "trace",
                                    "times": times.tolist()}},
                     "training": {{"kind": "off"}}}}}}
cursor = TraceCursor(build_duo_fleet(), doc)
cursor.close()
params = SimParams(algo="default_policy", duration=float(times[-1]) + 5.0,
                   seed=0)
twin = Twin(build_duo_fleet(), params, cursor, store={store!r},
            chunk_steps=256)
twin.advance()
print("done without kill", twin.done)
'''


def test_sigkill_mid_ingest_resumes_byte_identical(duo, tmp_path):
    store = str(tmp_path / "store")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DCG_TWIN_TEST_KILL_AFTER="3")
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_CHILD.format(here=HERE, store=store)],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    t = _times(600, rate=5.0, seed=11)
    params = _params(t)
    cur = TraceCursor(duo, _doc(t))
    cur.close()
    twin = Twin(duo, params, cur, store=store, chunk_steps=CHUNK)
    assert twin.chunk == 3  # resumed from the last verified commit
    twin.advance()
    assert twin.done

    cur_b = TraceCursor(duo, _doc(t))
    cur_b.close()
    batch = Twin(duo, params, cur_b, chunk_steps=CHUNK)
    batch.advance()
    assert tree_mismatches(twin.state, batch.state) == []

    # the killed store fsck-passes, watermark recognized (not debris)
    sys.path.insert(0, os.path.join(HERE, "scripts"))
    import fsck_ckpt

    ok, bad = fsck_ckpt.fsck_store(store, fast=True)
    assert bad == []
    assert any("twin store" in line for line in ok)


def test_fingerprint_mismatch_refuses_resume(duo, tmp_path):
    t = _times(300)
    store = str(tmp_path / "store")
    cur = TraceCursor(duo, _doc(t))
    twin = Twin(duo, _params(t), cur, store=store, chunk_steps=CHUNK)
    twin.advance(max_chunks=1)
    other = SimParams(algo="eco_route", duration=float(t[-1]) + 5.0, seed=0)
    with pytest.raises(RuntimeError, match="different"):
        Twin(duo, other, TraceCursor(duo, _doc(t)), store=store,
             chunk_steps=CHUNK)


# ---------------------------------------------------------------------------
# RCA window + windowed store copy / replay_run steps=
# ---------------------------------------------------------------------------

def test_rca_window_reproduces_history(duo, tmp_path):
    from distributed_cluster_gpus_tpu.twin.service import twin_rca

    t = _times(500)
    store = str(tmp_path / "store")
    cur = TraceCursor(duo, _doc(t))
    twin = Twin(duo, _params(t), cur, store=store, chunk_steps=CHUNK)
    twin.advance(max_chunks=6)
    assert twin.chunk == 6
    rep = twin_rca(twin, 2, 5)
    assert rep["reproduced"] and rep["mismatches"] == []
    assert rep["chunks_replayed"] == 3
    assert rep["t_hi"] > rep["t_lo"] > 0.0
    with pytest.raises(ValueError):
        twin_rca(twin, 5, 2)


def test_copy_store_window(tmp_path):
    from distributed_cluster_gpus_tpu.sim.replay import (
        ReplayError, copy_store_window)
    from distributed_cluster_gpus_tpu.utils.checkpoint import (
        save_checkpoint, steps)

    src = str(tmp_path / "src")
    for s in range(1, 6):
        save_checkpoint(src, s, state={"x": np.arange(s)})
    dst = str(tmp_path / "dst")
    assert copy_store_window(src, dst, 2, 4) == 3
    assert steps(dst) == [2, 3, 4]
    # replay_run's empty-window guard fires before any engine work
    from distributed_cluster_gpus_tpu.sim.replay import replay_run

    with pytest.raises(ReplayError, match="no committed steps"):
        replay_run(None, None, src, str(tmp_path / "out_src"),
                   str(tmp_path / "out"), steps=(40, 50))


# ---------------------------------------------------------------------------
# service: request dispatch + gauges + the prom/jsonl export
# ---------------------------------------------------------------------------

def test_service_handles_and_gauges(duo, tmp_path):
    from distributed_cluster_gpus_tpu.obs.export import write_twin_metrics

    t = _times(300)
    twin = Twin(duo, _params(t), TraceCursor(duo, _doc(t)),
                chunk_steps=CHUNK)
    twin.advance(max_chunks=2)
    svc = TwinService(twin)

    st = svc.handle({"op": "status"})
    assert st["ok"] and st["result"]["chunk"] == 2

    bad = svc.handle({"op": "warp_core_breach"})
    assert not bad["ok"] and "unknown op" in bad["error"]

    bad = svc.handle({"op": "forecast",
                      "overlays": [{"kind": "sharknado"}]})
    assert not bad["ok"] and "sharknado" in bad["error"]

    bad = svc.handle({"op": "rca", "steps": [0, 1]})
    assert not bad["ok"]  # no store attached

    g = svc.gauges()
    assert set(g) == {"obs_twin_ingest_lag_s", "obs_twin_state_age_s",
                      "obs_twin_forks_served_total", "obs_twin_fork_p95_s"}
    out = str(tmp_path)
    write_twin_metrics(out, g)
    prom = open(os.path.join(out, "metrics.prom")).read()
    assert "dcg_obs_twin_ingest_lag_s" in prom
    assert "# TYPE dcg_obs_twin_forks_served_total counter" in prom
    rec = json.loads(open(os.path.join(out, "metrics.jsonl")).read())
    assert rec["obs_twin_forks_served_total"] == 0.0
    with pytest.raises(ValueError, match="unknown twin gauge"):
        write_twin_metrics(out, {"obs_twin_bogus": 1.0})


# ---------------------------------------------------------------------------
# satellites: --append CLI, ledger record kind
# ---------------------------------------------------------------------------

def test_validate_workload_append_cli(duo, tmp_path):
    sys.path.insert(0, os.path.join(HERE, "scripts"))
    import validate_workload

    t = _times(60)
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_doc(t[:30])))
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_seg(t[30:])))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_seg([0.5, 1.0])))

    rep = tmp_path / "report.json"
    rc = validate_workload.main(["--fleet", "duo", "--append",
                                 str(base), str(good),
                                 "--json", str(rep)])
    assert rc == 0
    doc = json.loads(rep.read_text())
    assert doc["schema"] == "dcg.lint_report.v1" and not doc["violations"]

    rc = validate_workload.main(["--fleet", "duo", "--append",
                                 str(base), str(bad),
                                 "--json", str(rep)])
    assert rc == 1
    doc = json.loads(rep.read_text())
    assert any("precedes the base trace's last" in v["message"]
               for v in doc["violations"])


def test_ledger_twin_latency_record():
    from distributed_cluster_gpus_tpu.analysis import ledger

    doc = {"twin_latency": {"fleet": "duo", "n_lanes": 5, "n_buckets": 5,
                            "horizon_s": 300.0, "p50_s": 0.42,
                            "p95_s": 0.61, "ev_s": 12345.6,
                            "events_forecast": 5186},
           "platform": "cpu"}
    recs = ledger.records_from("bench_results/twin_r19.json", doc)
    tl = [r for r in recs if r["kind"] == "twin_latency"]
    assert len(tl) == 1
    assert tl[0]["config"] == "duo/5lanes/h300.0s"
    assert tl[0]["ev_s"] == 12345.6
    assert tl[0]["p95_s"] == 0.61
    assert tl[0]["round"] == 19
    # the gate accepts the kind (banked best from an earlier round: the
    # gate deliberately never compares a record against its own source)
    banked = dict(tl[0], ev_s=20000.0,
                  source="bench_results/twin_r18.json")
    regressions = ledger.check([banked], tl, threshold=0.3,
                               kinds=("twin_latency",))
    assert regressions and regressions[0]["kind"] == "twin_latency"
    assert not ledger.check([dict(banked, ev_s=13000.0)], tl,
                            threshold=0.3, kinds=("twin_latency",))

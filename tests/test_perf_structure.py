"""Structural guards for the step program's op-count budget.

The TPU step is op-count bound (docs/perf_notes.md): wall time tracks the
number of (mostly small) ops in the scanned step body, so an accidental
re-introduction of per-branch duplicated work or an in-step while_loop is
a performance regression even when every correctness test stays green.
These tests pin the measured structure:

* step-body flattened eqn ceilings, pinned per queue layout (round-4
  measured: chsac 1,886 ring / 1,554 slab; joint_nf 1,752 ring / 1,304
  slab — ceilings leave ~6% headroom for benign drift).  The ring
  layout's extra eqns are almost all SCALAR record ops (11-float ring
  row reads/writes), while its O(R*J)-sized op count went DOWN (queue
  lengths became counter reads and the slab no longer carries waiting
  jobs) — the flat eqn count is a cruder cost proxy for rings, and the
  on-chip ring-vs-slab A/B (scripts/tpu_recovery.sh) is the decider;
* no `while` primitive inside the step body on the default (inversion
  pregen) path — the sinusoid thinning loop must stay out of the scan;
* the inversion pregen itself contains no sequential scan.
"""

import jax
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state


def flat_count(jaxpr):
    n = 0
    for q in jaxpr.eqns:
        n += 1
        for v in q.params.values():
            vs = v if isinstance(v, (list, tuple)) else [v]
            for x in vs:
                if hasattr(x, "jaxpr"):
                    n += flat_count(x.jaxpr)
    return n


def primitives(jaxpr, acc=None):
    acc = set() if acc is None else acc
    for q in jaxpr.eqns:
        acc.add(q.primitive.name)
        for v in q.params.values():
            vs = v if isinstance(v, (list, tuple)) else [v]
            for x in vs:
                if hasattr(x, "jaxpr"):
                    primitives(x.jaxpr, acc)
    return acc


def _trace(fleet, algo, policy=None, pp=None, queue_mode="ring",
           superstep_k=1, obs_enabled=False):
    params = SimParams(algo=algo, duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0,
                       queue_mode=queue_mode, queue_cap=256,
                       superstep_k=superstep_k, obs_enabled=obs_enabled)
    eng = Engine(fleet, params, policy_apply=policy)
    st = init_state(jax.random.key(0), fleet, params)
    jpr = jax.make_jaxpr(lambda s, p: eng._run_chunk(s, p, 8))(st, pp)
    scans = [q for q in jpr.jaxpr.eqns
             if q.primitive.name == "scan" and q.params["length"] == 8]
    # the main event scan is the one carrying the SimState (61+ outputs);
    # the amp>1 pregen fallback would add a second scan (none expected here)
    body = max((q.params["jaxpr"].jaxpr for q in scans),
               key=lambda b: len(b.eqns))
    return jpr.jaxpr, body, len(scans)


@pytest.fixture(scope="module")
def chsac_trace(fleet):
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)

    params = SimParams(algo="chsac_af", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0)
    cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
                    n_g=params.max_gpus_per_job,
                    constraints=default_constraints(500.0))
    sac = sac_init(cfg, jax.random.key(1))
    return {m: _trace(fleet, "chsac_af", policy=make_policy_apply(cfg),
                      pp=sac, queue_mode=m) for m in ("ring", "slab")}


def test_chsac_step_op_budget(chsac_trace):
    # re-pinned at round 6: the superstep's bit-identity guarantee needs
    # cross-program float determinism, which costs the singleton body a
    # deliberate ~9-15% — `fmul_pinned` contraction fences on the accrual/
    # power/event-time products and fixed-tree `dc_sum` reductions (XLA's
    # reduce order and LLVM's FMA contraction otherwise vary with fusion
    # context).  Round-4 history: 1,886 ring / 1,554 slab.
    for mode, ceiling, measured in (("ring", 2170, 2059),
                                    ("slab", 1900, 1803)):
        _, body, _ = chsac_trace[mode]
        n = flat_count(body)
        assert n <= ceiling, (
            f"chsac step body ({mode}) grew to {n} eqns (measured "
            f"{measured:,} at round 6); the TPU step is op-count bound "
            "— find what re-duplicated work")


def test_step_has_no_while_loop(chsac_trace):
    _, body, _ = chsac_trace["ring"]
    assert "while" not in primitives(body), (
        "a while_loop is back inside the scanned step body — under vmap "
        "every lane pays its max trip count every step (the sinusoid "
        "thinning loop was evicted by the inversion pregen)")


def test_inversion_pregen_has_no_scan(chsac_trace):
    _, _, n_scans = chsac_trace["ring"]
    assert n_scans == 1, (
        "the default |amp|<=1 pregen path must be fully parallel; a second "
        "length-n_steps scan means the sequential fallback leaked in")


def test_joint_nf_step_op_budget(fleet):
    # re-pinned at round 6 (determinism fences + fixed-tree dc_sum — see
    # the chsac budget note; round-4 history: 1,752 ring / 1,304 slab)
    for mode, ceiling, measured in (("ring", 1930, 1835),
                                    ("slab", 1580, 1500)):
        _, body, _ = _trace(fleet, "joint_nf", queue_mode=mode)
        n = flat_count(body)
        assert n <= ceiling, (
            f"joint_nf step body ({mode}) grew to {n} eqns (measured "
            f"{measured:,} at round 6)")


def test_superstep_per_event_eqn_budget(fleet):
    """Round-7 re-pin: the unified select-free body (no singleton lane
    riding a cond, so nothing is traced twice) drops the K-wide step to
    joint_nf-ring K1 1,841 / K4 2,741 / K8 3,673 eqns (round 6 two-lane:
    1,835 / 3,660 / 4,592) — per-event 685 at K=4 and 459 at K=8.  Ratio
    floors tightened accordingly (round 6: 0.5 / 0.40); absolute
    ceilings keep ~5% headroom for benign drift."""
    _, b1, _ = _trace(fleet, "joint_nf")
    _, b4, _ = _trace(fleet, "joint_nf", superstep_k=4)
    _, b8, _ = _trace(fleet, "joint_nf", superstep_k=8)
    n1, n4, n8 = flat_count(b1), flat_count(b4), flat_count(b8)
    assert n4 / 4 <= 0.40 * n1, (
        f"superstep K=4 body costs {n4 / 4:.0f} eqns/event vs {n1} "
        "singleton — the unified body stopped amortizing; find what "
        "re-duplicated work (selection payload? apply loop? a singleton "
        "lane sneaking back in?)")
    assert n8 / 8 <= 0.27 * n1, (n8, n1)
    for n, ceiling, measured in ((n1, 1930, 1841), (n4, 2880, 2741),
                                 (n8, 3860, 3673)):
        assert n <= ceiling, (
            f"superstep body grew to {n} eqns (measured {measured:,} at "
            "round 7)")


def test_obs_on_eqn_overhead_pinned(fleet):
    """Round-8 pin: in-graph telemetry (`SimParams.obs_enabled`) costs a
    FIXED per-step eqn block — masked arithmetic appended after the
    event handlers, identical at every K (measured +126 eqns at K in
    {1, 4, 8}: joint_nf-ring 1,841→1,967 / 2,741→2,867 / 3,673→3,799).
    K-independence is the design invariant: telemetry folds once per
    scan iteration, so coalescing amortizes it (per-event +31 eqns at
    K=4 ≈ +4.6%, inside the ≤5% acceptance gate).  A K-dependent delta
    means obs work leaked inside the per-slot apply loop."""
    deltas = {}
    for k in (1, 4):
        _, b_off, _ = _trace(fleet, "joint_nf", superstep_k=k)
        _, b_on, _ = _trace(fleet, "joint_nf", superstep_k=k,
                            obs_enabled=True)
        deltas[k] = flat_count(b_on) - flat_count(b_off)
        assert 0 < deltas[k] <= 180, (
            f"obs-on step body (K={k}) adds {deltas[k]} eqns (measured "
            "126 at round 8); the telemetry fold is budgeted as a fixed "
            "per-step block — find what grew")
    assert deltas[1] == deltas[4], (
        f"obs eqn overhead is K-dependent ({deltas}): telemetry work "
        "leaked into the per-slot superstep apply loop instead of the "
        "once-per-iteration fold")
    # the superstep's select-free pin must survive obs-on: the telemetry
    # fold is masked arithmetic, never a cond
    _, b4_on, _ = _trace(fleet, "joint_nf", superstep_k=4,
                         obs_enabled=True)
    assert "cond" not in primitives(b4_on), (
        "obs-on K=4 body contains a cond — the telemetry fold must stay "
        "branch-free (see test_superstep_program_is_select_free)")


def test_superstep_program_is_select_free(fleet):
    """Round-7 tentpole pin: the K>1 step program dispatches through ONE
    unified body — no `cond` primitive (lax.switch is the same
    primitive) anywhere, unbatched or vmapped.  Round 6's
    fused/singleton `lax.cond` lowered under vmap to a select executing
    BOTH bodies every iteration, which is why only +16% of the
    structural 2x landed (docs/perf_notes.md round 7).  The unbatched
    assertion is the strong one (batching a cond-free program cannot
    introduce a cond); the batched jaxpr is checked too because that is
    the program the vmapped rollout bench actually runs."""
    from distributed_cluster_gpus_tpu.parallel.rollout import batched_init

    params = SimParams(algo="joint_nf", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0,
                       queue_mode="ring", queue_cap=256, superstep_k=4)
    fleet_local = fleet
    eng = Engine(fleet_local, params)
    st = init_state(jax.random.key(0), fleet_local, params)
    jpr = jax.make_jaxpr(lambda s: eng._run_chunk(s, None, 8))(st)
    assert "cond" not in primitives(jpr.jaxpr), (
        "a cond/switch primitive is back in the K>1 program — the "
        "select-free unified body regressed to branch dispatch")
    sts = batched_init(fleet_local, params, 2)
    jpr_b = jax.make_jaxpr(
        jax.vmap(lambda s: eng._run_chunk(s, None, 8)))(sts)
    assert "cond" not in primitives(jpr_b.jaxpr)


def test_superstep_k1_compiles_the_legacy_program(fleet):
    """superstep_k=1 must trace to a byte-identical jaxpr vs the default
    params — the superstep machinery is compile-gated behind K > 1, and
    nothing of it may leak into the singleton program."""
    jpr_default, _, _ = _trace(fleet, "joint_nf")
    jpr_k1, _, _ = _trace(fleet, "joint_nf", superstep_k=1)
    assert str(jpr_k1) == str(jpr_default)


def branch_writes(jaxpr, shape, in_branch=False, acc=None):
    """Collect write primitives (dus/scatter) of ``shape``-shaped arrays that
    occur inside a cond/switch branch sub-jaxpr."""
    acc = [] if acc is None else acc
    for q in jaxpr.eqns:
        is_branch_op = q.primitive.name == "cond"
        if in_branch and q.primitive.name.startswith(("dynamic_update_slice",
                                                      "scatter")):
            if any(tuple(v.aval.shape) == shape for v in q.outvars):
                acc.append(q.primitive.name)
        for v in q.params.values():
            vs = v if isinstance(v, (list, tuple)) else [v]
            for x in vs:
                if hasattr(x, "jaxpr"):
                    branch_writes(x.jaxpr, shape,
                                  in_branch or is_branch_op, acc)
    return acc


def test_no_ring_writes_inside_branches(fleet):
    """VERDICT r04 item 4: the elastic+ring configuration must not write
    `queues.recs` inside any cond/switch branch — a branched ring write
    forces a whole-ring select every step (4 ev/s at deep queue_cap).
    Elastic resume failures instead wait QUEUED in the slab and migrate
    post-switch (`Engine._migrate_elastic_queued`)."""
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)

    params = SimParams(algo="chsac_af", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0,
                       elastic_scaling=True, queue_mode="ring", queue_cap=256)
    cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
                    n_g=params.max_gpus_per_job,
                    constraints=default_constraints(500.0))
    sac = sac_init(cfg, jax.random.key(1))
    eng = Engine(fleet, params, policy_apply=make_policy_apply(cfg))
    st = init_state(jax.random.key(0), fleet, params)
    recs_shape = tuple(st.queues.recs.shape)
    jpr = jax.make_jaxpr(lambda s, p: eng._run_chunk(s, p, 8))(st, sac)
    hits = branch_writes(jpr.jaxpr, recs_shape)
    assert not hits, (
        f"ring-record writes inside cond/switch branches: {hits} — these "
        "force a whole-ring select per step (ring-mutation note above "
        "Engine._zero_push)")

"""Structural guards for the step program's op-count budget.

The TPU step is op-count bound (docs/perf_notes.md): wall time tracks the
number of (mostly small) ops in the scanned step body, so an accidental
re-introduction of per-branch duplicated work or an in-step while_loop is
a performance regression even when every correctness test stays green.
These tests pin the measured structure:

* step-body flattened eqn ceilings, pinned per canonical config.  Since
  PR 13 (dcg-lint) the ceilings are GENERATED, not hand-edited: the
  measured eqn counts live in
  distributed_cluster_gpus_tpu/analysis/baselines.json (re-banked by
  `scripts/lint_graph.py --update-baselines`, which prints the
  per-class diff), and `analysis.lint.ceiling_for` applies the banked
  headroom.  The ring layout's extra eqns are almost all SCALAR record
  ops (11-float ring row reads/writes), while its O(R*J)-sized op count
  went DOWN (queue lengths became counter reads and the slab no longer
  carries waiting jobs) — the flat eqn count is a cruder cost proxy for
  rings, and the on-chip ring-vs-slab A/B (scripts/tpu_recovery.sh) is
  the decider;
* no `while` primitive inside the step body — since round 10 (workload
  compiler) EVERY stream kind and backend pregenerates ahead of the
  scan, so the pin is unconditional (no in-step draw path exists);
* the pregen prologue's only sequential component is the 1-add-per-step
  prefix fold (the chunk-invariance carry); the expensive generators
  (bisection inversion, searchsorted timelines, size sampling) stay
  fully parallel over the table.

The flatten/visit core is shared with the linter and the census
(analysis.walker): one flattening rule, or the pins stop being
comparable to the banked baselines.
"""

import jax
import pytest

from distributed_cluster_gpus_tpu.analysis.lint import (
    ceiling_for, load_baselines, measured_for)
from distributed_cluster_gpus_tpu.analysis.walker import (
    flat_count, primitives)
from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

BASELINES = load_baselines()


def _pin(config_id):
    """(ceiling, measured) for one canonical config, from the generated
    baselines — never a hand-edited constant."""
    return (ceiling_for(config_id, BASELINES),
            measured_for(config_id, BASELINES))


def _trace(fleet, algo, policy=None, pp=None, queue_mode="ring",
           superstep_k=1, obs_enabled=False, workload=None):
    params = SimParams(algo=algo, duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0,
                       queue_mode=queue_mode, queue_cap=256,
                       superstep_k=superstep_k, obs_enabled=obs_enabled,
                       workload=workload)
    eng = Engine(fleet, params, policy_apply=policy)
    st = init_state(jax.random.key(0), fleet, params)
    jpr = jax.make_jaxpr(lambda s, p: eng._run_chunk(s, p, 8))(st, pp)
    scans = [q for q in jpr.jaxpr.eqns
             if q.primitive.name == "scan" and q.params["length"] == 8]
    # the main event scan is the one carrying the SimState (61+ outputs);
    # the workload pregen adds its tiny prefix-fold scan (and, for
    # thinning streams only, the sequential replay scan) ahead of it
    body = max((q.params["jaxpr"].jaxpr for q in scans),
               key=lambda b: len(b.eqns))
    return jpr.jaxpr, body, scans


@pytest.fixture(scope="module")
def chsac_trace(fleet):
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)

    params = SimParams(algo="chsac_af", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0)
    cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
                    n_g=params.max_gpus_per_job,
                    constraints=default_constraints(500.0))
    sac = sac_init(cfg, jax.random.key(1))
    return {m: _trace(fleet, "chsac_af", policy=make_policy_apply(cfg),
                      pp=sac, queue_mode=m) for m in ("ring", "slab")}


def test_chsac_step_op_budget(chsac_trace):
    # ceilings generated from analysis/baselines.json since round 13
    # (PR 13 re-banked after the weak-type/fence sweep).  History:
    # round 4 1,886 ring / 1,554 slab; rounds 6-8 2,059 / 1,803;
    # round 12 1,800 / 1,538.
    for mode in ("ring", "slab"):
        ceiling, measured = _pin(f"chsac_af/{mode}/K1")
        _, body, _ = chsac_trace[mode]
        n = flat_count(body)
        assert n <= ceiling, (
            f"chsac step body ({mode}) grew to {n} eqns (baseline "
            f"{measured:,}); the TPU step is op-count bound — find what "
            "re-duplicated work, or re-bank with --update-baselines")


def test_step_has_no_while_loop(chsac_trace):
    _, body, _ = chsac_trace["ring"]
    assert "while" not in primitives(body), (
        "a while_loop is back inside the scanned step body — under vmap "
        "every lane pays its max trip count every step (the sinusoid "
        "thinning loop was evicted by the inversion pregen)")


def test_inversion_pregen_stays_parallel(chsac_trace):
    """Round-10 re-pin: the default |amp|<=1 pregen path carries exactly
    ONE sequential component besides the event scan — the 1-add-per-step
    prefix fold whose carry makes chunking bit-invariant.  The expensive
    generators (bisection inversion, size sampling) must stay fully
    parallel: a fat second scan means the sequential thinning fallback
    (or a bisection-inside-scan regression) leaked into the default
    path."""
    _, body, scans = chsac_trace["ring"]
    assert len(scans) == 2, (
        f"{len(scans)} length-n_steps scans (expected the event scan + "
        "the tiny prefix fold)")
    others = [q.params["jaxpr"].jaxpr for q in scans
              if q.params["jaxpr"].jaxpr is not body]
    for b in others:
        assert flat_count(b) <= 4, (
            f"pregen prologue scan carries {flat_count(b)} eqns — the "
            "prefix fold is budgeted at one add per step; heavy "
            "generation must stay vectorized over the table")


def test_workload_signal_step_budget(fleet):
    """Round-10 pin, re-pinned at round 12: a trace-driven workload with
    time-varying price/carbon signals (rate-timeline streams + signal
    timelines — the flash_crowd preset) stays while-free in the step
    body and its signal overhead is a fixed block: sampled price/CI
    gathers at the eco sites, the cost/carbon accrual, and two extra
    cluster columns (round 12: carbon_cost 1,645 eqns / eco_route 1,603,
    down from 1,821 / 1,667 — the universal xfer drain-merge).  Signal
    runs are superstep-ELIGIBLE since round 12: the K=4 program accrues
    the cost integral per sub-step and must keep amortizing (per-event
    well under the singleton).  A while here means a workload draw
    leaked back into the scan; a fat regression means the signal
    sampling stopped being cheap gathers."""
    from distributed_cluster_gpus_tpu.workload import make_preset

    wl = make_preset("flash_crowd", fleet, horizon_s=600.0)
    for algo in ("carbon_cost", "eco_route"):
        ceiling, measured = _pin(f"{algo}+signals/ring/K1")
        _, body, scans = _trace(fleet, algo, workload=wl)
        assert "while" not in primitives(body), (
            f"{algo}: a while_loop is inside the signal-workload step "
            "body — every workload draw must live in the pregen tables")
        n = flat_count(body)
        assert n <= ceiling, (
            f"{algo} signals-on step body grew to {n} eqns (baseline "
            f"{measured:,})")
        assert len(scans) == 2, (
            f"{algo}: {len(scans)} length-n_steps scans (event scan + "
            "prefix fold expected; rate timelines invert via "
            "searchsorted, never a replay scan)")
    # the newly eligible signal superstep: K=4 fused body with the
    # per-sub-step cost/carbon accrual — cond-free like every K>1 program
    ceiling4, measured4 = _pin("carbon_cost+signals/ring/K4")
    _, b4, _ = _trace(fleet, "carbon_cost", workload=wl, superstep_k=4)
    n4 = flat_count(b4)
    assert n4 <= ceiling4, (
        f"carbon_cost signals K=4 body grew to {n4} eqns (baseline "
        f"{measured4:,})")
    assert n4 / 4 < flat_count(body), "signal superstep stopped amortizing"
    assert "cond" not in primitives(b4)


def test_joint_nf_step_op_budget(fleet):
    # ceilings generated from analysis/baselines.json since round 13.
    # History: round 4 1,752 ring / 1,304 slab; rounds 6-8 1,835 /
    # 1,500; round 12 1,436 / 1,037 (xfer rides the shared drain, dead
    # start writes compiled out).
    for mode in ("ring", "slab"):
        ceiling, measured = _pin(f"joint_nf/{mode}/K1")
        _, body, _ = _trace(fleet, "joint_nf", queue_mode=mode)
        n = flat_count(body)
        assert n <= ceiling, (
            f"joint_nf step body ({mode}) grew to {n} eqns (baseline "
            f"{measured:,})")


def test_superstep_per_event_eqn_budget(fleet):
    """Round-12 re-pin (universal fast path): the K=1 body shrank again
    (xfer rides the shared drain, dead start writes compiled out:
    1,521 -> 1,436) while the K>1 unified body is unchanged (its drain
    always carried the merged chain) — joint_nf-ring K1 1,436 / K4
    2,567 / K8 3,459 eqns, per-event 642 at K=4 and 432 at K=8.  The
    RATIO floors loosen again (0.45 -> 0.46, 0.31 -> 0.32) for the same
    round-9 reason: only the singleton curve dropped, so the
    per-event-vs-singleton ratio drifts up even though the absolute
    curves never grew — the absolute ceilings are the regression guard,
    the ratios only catch amortization collapse."""
    _, b1, _ = _trace(fleet, "joint_nf")
    _, b4, _ = _trace(fleet, "joint_nf", superstep_k=4)
    _, b8, _ = _trace(fleet, "joint_nf", superstep_k=8)
    n1, n4, n8 = flat_count(b1), flat_count(b4), flat_count(b8)
    assert n4 / 4 <= 0.46 * n1, (
        f"superstep K=4 body costs {n4 / 4:.0f} eqns/event vs {n1} "
        "singleton — the unified body stopped amortizing; find what "
        "re-duplicated work (selection payload? apply loop? a singleton "
        "lane sneaking back in?)")
    assert n8 / 8 <= 0.32 * n1, (n8, n1)
    for n, cfg in ((n1, "joint_nf/ring/K1"), (n4, "joint_nf/ring/K4"),
                   (n8, "joint_nf/ring/K8")):
        ceiling, measured = _pin(cfg)
        assert n <= ceiling, (
            f"superstep body ({cfg}) grew to {n} eqns (baseline "
            f"{measured:,})")


def test_fault_and_bandit_fastpath_budget(fleet):
    """Round-12 pins for the newly eligible families.

    * fault runs plan AND superstep: the K=1 planner program carries the
      EV_FAULT branch's in-branch masked writes plus the migration sweep
      (measured 2,279 ring / 2,031 slab — ring MERGES the deferred
      slot-0 drain with the promoted migration drain into one masked
      call, which is what puts the planner program 12% UNDER the
      2,578-eqn legacy ring program), and the K=4 fused body stays
      cond-free and amortizing (3,369 ring, per-event 842 vs the 2,279
      singleton);
    * bandit plans: the arm state rides the plan carry and the masked
      drain's predicated select/update (measured 1,468 ring / 1,069
      slab — within ~2% of joint_nf's planner program, vs the legacy
      cond-dispatch program it compiled before round 12)."""
    from distributed_cluster_gpus_tpu.configs.paper import (
        build_incident_faults)

    faults = build_incident_faults(10.0, 20.0)

    def trace_faulted(qm, k):
        from distributed_cluster_gpus_tpu.analysis.walker import (
            main_scan_body)

        params = SimParams(algo="default_policy", duration=1e9,
                           log_interval=20.0, inf_mode="sinusoid",
                           inf_rate=6.0, trn_mode="poisson", trn_rate=0.1,
                           job_cap=128, lat_window=512, seed=0,
                           queue_mode=qm, queue_cap=256, superstep_k=k,
                           faults=faults)
        eng = Engine(fleet, params)
        st = init_state(jax.random.key(0), fleet, params)
        jpr = jax.make_jaxpr(lambda s: eng._run_chunk(s, None, 8))(st)
        return main_scan_body(jpr, 8).params["jaxpr"].jaxpr

    for qm in ("ring", "slab"):
        ceiling, measured = _pin(f"fault/{qm}/K1")
        n = flat_count(trace_faulted(qm, 1))
        assert n <= ceiling, (
            f"faulted planner body ({qm}) grew to {n} eqns (baseline "
            f"{measured:,})")
    b4 = trace_faulted("ring", 4)
    n4, n1 = flat_count(b4), flat_count(trace_faulted("ring", 1))
    ceiling4, measured4 = _pin("fault/ring/K4")
    assert n4 <= ceiling4, (
        f"faulted K=4 body grew to {n4} eqns (baseline {measured4:,})")
    assert n4 / 4 < n1, "fault superstep stopped amortizing"
    assert "cond" not in primitives(b4), (
        "the faulted K=4 program regressed to branch dispatch — "
        "`_handle_fault` must stay a masked slot-0 tail")

    for qm in ("ring", "slab"):
        ceiling, measured = _pin(f"bandit/{qm}/K1")
        _, body, _ = _trace(fleet, "bandit", queue_mode=qm)
        n = flat_count(body)
        assert n <= ceiling, (
            f"bandit planner body ({qm}) grew to {n} eqns (baseline "
            f"{measured:,})")


def test_obs_on_eqn_overhead_pinned(fleet):
    """Round-8 pin: in-graph telemetry (`SimParams.obs_enabled`) costs a
    FIXED per-step eqn block — masked arithmetic appended after the
    event handlers, identical at every K (measured +126 eqns at K in
    {1, 4, 8}: joint_nf-ring 1,841→1,967 / 2,741→2,867 / 3,673→3,799).
    K-independence is the design invariant: telemetry folds once per
    scan iteration, so coalescing amortizes it (per-event +31 eqns at
    K=4 ≈ +4.6%, inside the ≤5% acceptance gate).  A K-dependent delta
    means obs work leaked inside the per-slot apply loop."""
    delta_ceiling, delta_measured = _pin("joint_nf/ring/obs-delta")
    deltas = {}
    for k in (1, 4):
        _, b_off, _ = _trace(fleet, "joint_nf", superstep_k=k)
        _, b_on, _ = _trace(fleet, "joint_nf", superstep_k=k,
                            obs_enabled=True)
        deltas[k] = flat_count(b_on) - flat_count(b_off)
        assert 0 < deltas[k] <= delta_ceiling, (
            f"obs-on step body (K={k}) adds {deltas[k]} eqns (baseline "
            f"delta {delta_measured}); the telemetry fold is budgeted as "
            "a fixed per-step block — find what grew")
    # K-independence up to the O(1) fired/kind_counts plumbing: the
    # singleton gates on a scalar `done`, the superstep folds its [K]
    # applied-mask — a few eqns of difference by construction.  The
    # guarded failure mode (telemetry leaking into the per-slot apply
    # loop) costs ~tens of eqns PER K and blows far past this tolerance.
    assert abs(deltas[1] - deltas[4]) <= 2, (
        f"obs eqn overhead is K-dependent ({deltas}): telemetry work "
        "leaked into the per-slot superstep apply loop instead of the "
        "once-per-iteration fold")
    # the superstep's select-free pin must survive obs-on: the telemetry
    # fold is masked arithmetic, never a cond
    _, b4_on, _ = _trace(fleet, "joint_nf", superstep_k=4,
                         obs_enabled=True)
    assert "cond" not in primitives(b4_on), (
        "obs-on K=4 body contains a cond — the telemetry fold must stay "
        "branch-free (see test_superstep_program_is_select_free)")


def test_superstep_program_is_select_free(fleet):
    """Round-7 tentpole pin: the K>1 step program dispatches through ONE
    unified body — no `cond` primitive (lax.switch is the same
    primitive) anywhere, unbatched or vmapped.  Round 6's
    fused/singleton `lax.cond` lowered under vmap to a select executing
    BOTH bodies every iteration, which is why only +16% of the
    structural 2x landed (docs/perf_notes.md round 7).  The unbatched
    assertion is the strong one (batching a cond-free program cannot
    introduce a cond); the batched jaxpr is checked too because that is
    the program the vmapped rollout bench actually runs."""
    from distributed_cluster_gpus_tpu.parallel.rollout import batched_init

    params = SimParams(algo="joint_nf", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0,
                       queue_mode="ring", queue_cap=256, superstep_k=4)
    fleet_local = fleet
    eng = Engine(fleet_local, params)
    st = init_state(jax.random.key(0), fleet_local, params)
    jpr = jax.make_jaxpr(lambda s: eng._run_chunk(s, None, 8))(st)
    assert "cond" not in primitives(jpr.jaxpr), (
        "a cond/switch primitive is back in the K>1 program — the "
        "select-free unified body regressed to branch dispatch")
    sts = batched_init(fleet_local, params, 2)
    jpr_b = jax.make_jaxpr(
        jax.vmap(lambda s: eng._run_chunk(s, None, 8)))(sts)
    assert "cond" not in primitives(jpr_b.jaxpr)


def test_superstep_k1_compiles_the_legacy_program(fleet):
    """superstep_k=1 must trace to a byte-identical jaxpr vs the default
    params — the superstep machinery is compile-gated behind K > 1, and
    nothing of it may leak into the singleton program."""
    jpr_default, _, _ = _trace(fleet, "joint_nf")
    jpr_k1, _, _ = _trace(fleet, "joint_nf", superstep_k=1)
    assert str(jpr_k1) == str(jpr_default)


def branch_writes(jaxpr, shape, in_branch=False, acc=None):
    """Collect write primitives (dus/scatter) of ``shape``-shaped arrays that
    occur inside a cond/switch branch sub-jaxpr."""
    acc = [] if acc is None else acc
    for q in jaxpr.eqns:
        is_branch_op = q.primitive.name == "cond"
        if in_branch and q.primitive.name.startswith(("dynamic_update_slice",
                                                      "scatter")):
            if any(tuple(v.aval.shape) == shape for v in q.outvars):
                acc.append(q.primitive.name)
        for v in q.params.values():
            vs = v if isinstance(v, (list, tuple)) else [v]
            for x in vs:
                if hasattr(x, "jaxpr"):
                    branch_writes(x.jaxpr, shape,
                                  in_branch or is_branch_op, acc)
    return acc


def slab_selects(jaxpr, J, in_branch=False, acc=None):
    """Count select_n eqns with a [J]-leading output shape, split into
    (outside-branch, inside-cond-branch) — recursing through pjit
    wrappers but NOT into scan/while bodies (the drain loop legitimately
    owns its per-iteration merged write chain)."""
    acc = [0, 0] if acc is None else acc
    for q in jaxpr.eqns:
        if q.primitive.name == "select_n" and any(
                v.aval.shape[:1] == (J,) for v in q.outvars):
            acc[1 if in_branch else 0] += 1
        if q.primitive.name in ("scan", "while"):
            continue
        is_branch = q.primitive.name == "cond"
        for v in q.params.values():
            vs = v if isinstance(v, (list, tuple)) else [v]
            for x in vs:
                if hasattr(x, "jaxpr"):
                    slab_selects(x.jaxpr, J, in_branch or is_branch, acc)
    return acc


def test_write_plan_one_commit_per_step(fleet, chsac_trace):
    """Round-9 tentpole pin: planner programs carry NO [J]-shaped selects
    inside any event/tail switch branch — every slab write (and the [J]
    read-side selects) lives at step level, where the shared commit
    applies ONE masked write per slab field.  Budgets: joint_nf = one
    `_commit_plan` (19 slab-field writes) + the step head's read-side
    selects; chsac adds the `_commit_tail` merge (the policy tail's
    route/materialize writes + the start commit).  The few in-branch [J]
    selects left are READ-side (the log tick's per-job throughput
    vector, the slab-mode drain's queue argmin inputs); the write chains
    that used to live there are gone, and a branch that regrows a
    private `slab_write` chain trips the in-branch budget immediately."""
    J = 128
    for algo, qm in (("joint_nf", "ring"), ("joint_nf", "slab"),
                     ("default_policy", "ring")):
        _, body, _ = _trace(fleet, algo, queue_mode=qm)
        top, inside = slab_selects(body, J)
        assert inside <= 3, (
            f"{algo}/{qm}: {inside} [J]-shaped selects inside switch "
            "branches (measured 3 read-side at round 9) — a handler is "
            "writing the slab in-branch again instead of planning; under "
            "vmap every branch executes every step")
        assert top <= 32, (
            f"{algo}/{qm}: {top} step-level [J] selects (measured 25 at "
            "round 9: one commit write per slab field + the step head's "
            "read-side selects) — the shared commit is no longer shared")
    for qm, inside_ceiling, top_ceiling in (("ring", 3, 58),
                                            ("slab", 5, 50)):
        _, body, _ = chsac_trace[qm]
        top, inside = slab_selects(body, J)
        assert inside <= inside_ceiling, (
            f"chsac/{qm}: {inside} [J] selects inside switch branches "
            "(read-side only at round 9)")
        assert top <= top_ceiling, (
            f"chsac/{qm}: {top} step-level [J] selects (measured 50/43 "
            "at round 9: event commit + tail commit)")


def test_no_ring_writes_inside_branches(fleet):
    """VERDICT r04 item 4: the elastic+ring configuration must not write
    `queues.recs` inside any cond/switch branch — a branched ring write
    forces a whole-ring select every step (4 ev/s at deep queue_cap).
    Elastic resume failures instead wait QUEUED in the slab and migrate
    post-switch (`Engine._migrate_elastic_queued`)."""
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)

    params = SimParams(algo="chsac_af", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=128, lat_window=512, seed=0,
                       elastic_scaling=True, queue_mode="ring", queue_cap=256)
    cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
                    n_g=params.max_gpus_per_job,
                    constraints=default_constraints(500.0))
    sac = sac_init(cfg, jax.random.key(1))
    eng = Engine(fleet, params, policy_apply=make_policy_apply(cfg))
    st = init_state(jax.random.key(0), fleet, params)
    recs_shape = tuple(st.queues.recs.shape)
    jpr = jax.make_jaxpr(lambda s, p: eng._run_chunk(s, p, 8))(st, sac)
    hits = branch_writes(jpr.jaxpr, recs_shape)
    assert not hits, (
        f"ring-record writes inside cond/switch branches: {hits} — these "
        "force a whole-ring select per step (ring-mutation note above "
        "Engine._zero_push)")


def _load_census_mod():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "count_step_ops",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "count_step_ops.py"))
    census_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(census_mod)
    return census_mod


def test_eligibility_residue_pinned(fleet):
    """Round-12 pin: the static fast-path ineligibility lists never
    silently regrow.  The census (`count_step_ops.py --eligibility`)
    must show EXACTLY the irreducible residue — superstep excludes only
    {chsac_af, bandit, weighted routing}, the planner excludes NOTHING —
    and the Engine flags must agree with the static report (a gate that
    starts rejecting eligible configs again, or a new config family
    landing ineligible, both trip here before a golden ever runs)."""
    census_mod = _load_census_mod()
    rows = {r["config"]: r for r in census_mod.eligibility_report(fleet)}
    residue = {  # config -> the one gate allowed to reject it
        "bandit": "bandit_state",
        "bandit+faults": "bandit_state",
        "weighted_router": "queue_coupled_routing",
        "chsac_af": "rl_policy_tail",
        "chsac_af+elastic": "rl_policy_tail",
        "chsac_af+faults": "rl_policy_tail",
    }
    for name, r in rows.items():
        assert not r["planner_reasons"], (
            f"{name}: the planner ineligibility residue regrew — round "
            f"12 pinned it EMPTY, got {r['planner_reasons']}")
        if name in residue:
            gates = [why.split(":")[0] for why in r["superstep_reasons"]]
            assert gates == [residue[name]], (
                f"{name}: superstep residue drifted — expected exactly "
                f"[{residue[name]}], got {r['superstep_reasons']}")
        else:
            assert r["superstep_eligible"], (
                f"{name}: a newly eligible family regressed to the "
                f"legacy program: {r['superstep_reasons']}")
    assert set(residue) <= set(rows), "census lost a pinned config row"

    # the Engine flags must agree with the static report: the fast-path
    # programs compile BY DEFAULT for the round-12 families
    census_rows = census_mod.eligibility_configs(fleet)
    import dataclasses

    for name, params in census_rows:
        params = dataclasses.replace(params, superstep_k=4)
        kw = ({"policy_apply": lambda *a: (0, 0)}
              if params.algo == "chsac_af" else {})
        eng = Engine(fleet, params, **kw)
        assert eng.superstep_on == (name not in residue), name
        assert eng.planner_on, name


def test_op_census_smoke(fleet):
    """Tier-1 smoke for scripts/count_step_ops.py: the census tool loads,
    its classes PARTITION the flattened eqn count (its "eqns" is the
    same metric the ceilings above pin), and the write-plan program's
    class-level signature holds — K=1 keeps exactly the event switch as
    its one cond and no while, the K=4 plan commits through scatters and
    stays cond-free.  bench.py banks `census_matrix()` with this same
    counter, so a drifted class split shows up here before a banked
    round does."""
    census_mod = _load_census_mod()

    _, body, _ = _trace(fleet, "joint_nf", queue_mode="ring")
    c1 = census_mod.op_census(body)
    assert c1["eqns"] == flat_count(body), (
        "census total diverged from flat_count — the two flattening "
        "rules must stay identical or banked censuses stop being "
        "comparable to the pinned ceilings")
    class_sum = sum(v for k, v in c1.items() if k != "eqns")
    assert class_sum == c1["eqns"], (c1, "classes must partition eqns")
    assert c1["cond"] == 1 and c1["while"] == 0, (
        f"K=1 planner program census {c1}: expected exactly the event "
        "switch as the one cond and no in-step while loop")

    c4 = census_mod.step_census(fleet, "joint_nf", superstep_k=4)
    assert c4["cond"] == 0, (
        f"K=4 census {c4}: the select-free superstep regressed")
    assert c4["scatter"] > 0, (
        f"K=4 census {c4}: the K-row plan must commit via scatters")
    assert c4["per_event"] < c1["eqns"], "superstep stopped amortizing"

"""Pipelined host drain (round 7): overlap, ordering, and byte-identity.

`sim.io.run_simulation` dispatches chunk N+1 before fetching chunk N's
emissions (one batched `jax.device_get`) and renders CSVs on a bounded
background writer (`AsyncCSVDrain`), so per chunk the wall time is
~max(device rollout, host render) instead of their sum.  The contracts
tested here:

* the background writer really overlaps: with a synthetically slow
  writer, the submitting loop's visible io wall time is far below the
  serial render total (the PhaseTimer satellite of ISSUE round 7);
* FIFO ordering + byte-identity: the pipelined loop writes exactly the
  bytes a fully serial drain writes, and returns the same final state;
* worker errors surface instead of silently truncating logs.
"""

import filecmp
import time

import jax
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
from distributed_cluster_gpus_tpu.sim.io import (AsyncCSVDrain,
                                                 AsyncLineDrain, CSVWriters,
                                                 drain_emissions,
                                                 run_simulation)
from distributed_cluster_gpus_tpu.obs.trace import PhaseTimer


def test_async_drain_overlaps_slow_writer():
    """io wall-phase on the submitting side must be far below the serial
    drain time when the writer is slow — the render happens off-thread
    while the submitter 'computes' (sleeps, standing in for the device)."""
    RENDER_S, CHUNKS = 0.08, 6
    rendered = []

    def slow_drain(em, writers):
        time.sleep(RENDER_S)
        rendered.append(em["i"])
        return {"job_rows": 1}

    drainer = AsyncCSVDrain(None, drain_fn=slow_drain)
    timer = PhaseTimer()
    t0 = time.perf_counter()
    for i in range(CHUNKS):
        with timer.phase("io"):
            drainer.submit({"i": i})
        time.sleep(RENDER_S)  # the overlapped "rollout"
    drainer.close()
    wall = time.perf_counter() - t0
    serial = 2 * RENDER_S * CHUNKS  # render + compute, fully additive
    assert rendered == list(range(CHUNKS))  # FIFO order preserved
    assert drainer.render_seconds >= RENDER_S * CHUNKS * 0.9
    # visible io = enqueue only; the render ran behind the sleeps
    assert timer.totals["io"] < 0.5 * drainer.render_seconds, (
        f"io wall-phase {timer.totals['io']:.3f}s should be far below the "
        f"worker's render total {drainer.render_seconds:.3f}s")
    # overlap bound with slack for CI scheduler noise: a fully serial
    # loop cannot beat `serial` even in principle, so demanding one
    # render-time of saving still proves the pipeline while tolerating
    # a few hundred ms of stalls on a loaded 2-core box
    assert wall < serial - RENDER_S, (
        f"pipelined wall {wall:.3f}s vs serial {serial:.3f}s — no overlap")
    assert drainer.rows["job_rows"] == CHUNKS


def test_async_drain_propagates_worker_errors():
    def boom(em, writers):
        raise ValueError("disk full")

    drainer = AsyncCSVDrain(None, drain_fn=boom)
    drainer.submit({})
    with pytest.raises(RuntimeError, match="background CSV drain"):
        # the error lands on the next submit or on close, whichever first
        for _ in range(10):
            time.sleep(0.02)
            drainer.submit({})
        drainer.close()


def test_async_drain_abort_drops_queue_and_swallows_errors():
    """close(abort=True) — the exception-unwind path — must return fast
    (queued chunks dropped, not rendered) and never raise, so a deferred
    writer error cannot replace the caller's in-flight exception."""
    RENDER_S = 0.2

    def slow_then_boom(em, writers):
        time.sleep(RENDER_S)
        raise ValueError("disk full")

    drainer = AsyncCSVDrain(None, maxsize=8, drain_fn=slow_then_boom)
    for i in range(4):
        drainer.submit({"i": i})
    t0 = time.perf_counter()
    drainer.close(abort=True)  # must not raise
    # at most the in-flight render finishes; the rest are dropped
    assert time.perf_counter() - t0 < 3 * RENDER_S


def test_line_drain_generic_error_propagation():
    """The AsyncLineDrain base (round 8: shared by the CSV drain and the
    obs exporters) keeps the same error contract with a one-arg drain_fn
    and reports its own name in the failure."""
    def boom(item):
        raise ValueError("disk full")

    drain = AsyncLineDrain(boom, name="obs drain")
    drain.submit({})
    with pytest.raises(RuntimeError, match="background obs drain"):
        for _ in range(10):
            time.sleep(0.02)
            drain.submit({})
        drain.close()


def test_line_drain_abort_and_counters():
    """Generic abort path: queued items are dropped, deferred errors are
    swallowed, and the counter dict accumulates whatever drain_fn
    returns (the obs exporters' row counts ride this)."""
    RENDER_S = 0.2
    seen = []

    def slow(item):
        time.sleep(RENDER_S)
        seen.append(item)
        return {"obs_rows": 2}

    drain = AsyncLineDrain(slow, maxsize=8)
    for i in range(4):
        drain.submit(i)
    t0 = time.perf_counter()
    drain.close(abort=True)  # must not raise, must not flush all 4
    assert time.perf_counter() - t0 < 3 * RENDER_S
    assert len(seen) < 4

    drain = AsyncLineDrain(slow)
    drain.submit("a")
    drain.close()
    assert drain.rows["obs_rows"] == 2


def test_csv_drain_legacy_signature_preserved():
    """AsyncCSVDrain stays a drop-in: two-arg drain_fn(em, writers),
    writers threaded through, default row counters present."""
    got = []

    def fn(em, writers):
        got.append((em, writers))
        return {"cluster_rows": 3}

    sentinel = object()
    drainer = AsyncCSVDrain(sentinel, drain_fn=fn)
    drainer.submit({"x": 1})
    drainer.close()
    assert got == [({"x": 1}, sentinel)]
    assert drainer.rows["cluster_rows"] == 3
    assert drainer.rows["job_rows"] == 0  # legacy counter keys survive


PIPE_KW = dict(algo="joint_nf", duration=40.0, log_interval=5.0,
               inf_mode="sinusoid", inf_rate=2.0, trn_mode="poisson",
               trn_rate=0.1, job_cap=64, lat_window=128, seed=7,
               queue_cap=128)


@pytest.mark.parametrize("superstep_k", [1, 4])
def test_pipelined_csv_bytes_match_serial(fleet, tmp_path, superstep_k):
    """The pipelined loop must write byte-identical CSVs to a fully
    serial dispatch-then-drain loop, and return the same final state —
    multi-chunk so the dispatch-ahead ordering is actually exercised."""
    params = SimParams(superstep_k=superstep_k, **PIPE_KW)

    pipe_dir = str(tmp_path / "pipelined")
    state_pipe = run_simulation(fleet, params, out_dir=pipe_dir,
                                chunk_steps=256)

    serial_dir = str(tmp_path / "serial")
    engine = Engine(fleet, params)
    state = init_state(jax.random.key(params.seed), fleet, params)
    writers = CSVWriters(serial_dir, fleet)
    for _ in range(10_000):
        state, emissions = engine.run_chunk(state, None, n_steps=256)
        drain_emissions(emissions, writers)
        if bool(state.done):
            break

    for name in ("cluster_log.csv", "job_log.csv"):
        assert filecmp.cmp(f"{pipe_dir}/{name}", f"{serial_dir}/{name}",
                           shallow=False), f"{name} differs"
    assert bool(state_pipe.done) and bool(state.done)
    for a, b in zip(jax.tree.leaves(state_pipe), jax.tree.leaves(state)):
        if jax.numpy.issubdtype(a.dtype, jax.dtypes.prng_key):
            a, b = jax.random.key_data(a), jax.random.key_data(b)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipelined_timer_records_phases(fleet, tmp_path):
    """The external-timer hook: dispatch/rollout/io/io_render all appear,
    and io_render (the hidden worker time) is recorded once."""
    params = SimParams(superstep_k=1, **PIPE_KW)
    timer = PhaseTimer()
    run_simulation(fleet, params, out_dir=str(tmp_path / "o"),
                   chunk_steps=256, timer=timer)
    for phase in ("dispatch", "rollout", "io", "io_render"):
        assert phase in timer.totals, f"missing phase {phase}"
    assert timer.counts["io_render"] == 1


# ---------------------------------------------------------------------------
# transient-IO retry (PR 8 satellite): EINTR/EAGAIN retried with backoff
# before propagating; anything else propagates immediately
# ---------------------------------------------------------------------------

def test_line_drain_retries_transient_io_errors():
    """A drain_fn interrupted by EINTR twice then succeeding must be
    retried to success: all rows land, nothing propagates."""
    import errno

    calls = []

    def flaky(item):
        calls.append(item)
        if len(calls) <= 2:
            raise OSError(errno.EINTR, "interrupted system call")
        return {"rows": 1}

    drain = AsyncLineDrain(flaky, io_backoff_s=0.001)
    drain.submit("chunk")
    drain.close()  # must not raise
    assert len(calls) == 3
    assert drain.rows == {"rows": 1}
    assert drain.io_retry_count == 2


def test_line_drain_transient_error_budget_exhausts():
    """A persistently-EINTR drain_fn propagates after the retry budget
    (the error must not be swallowed forever)."""
    import errno

    calls = []

    def always_eintr(item):
        calls.append(item)
        raise OSError(errno.EINTR, "interrupted system call")

    drain = AsyncLineDrain(always_eintr, io_retries=2, io_backoff_s=0.001)
    drain.submit("chunk")
    with pytest.raises(RuntimeError, match="background line drain"):
        drain.close()
    assert len(calls) == 3  # 1 attempt + 2 retries


def test_line_drain_non_transient_oserror_fails_fast():
    """ENOSPC is not transient: exactly one attempt, error propagates."""
    import errno

    calls = []

    def enospc(item):
        calls.append(item)
        raise OSError(errno.ENOSPC, "no space left on device")

    drain = AsyncLineDrain(enospc, io_retries=3, io_backoff_s=0.001)
    drain.submit("chunk")
    with pytest.raises(RuntimeError, match="background line drain"):
        drain.close()
    assert len(calls) == 1

"""Forensic replay of aborted runs (sim/replay.py + scripts/replay_abort.py).

The acceptance loop of the verified-checkpoint tentpole's replay half:
an abort bundle (forensic checkpoint + abort_context.json) re-executes
deterministically — the SAME probe trips at the SAME chunk, the re-run
state byte-matches the forensic snapshot, and the bisection emits the
minimal scan-step window.  A clean run replayed from a mid-run healthy
checkpoint byte-matches the original CSVs.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import run_sim
from distributed_cluster_gpus_tpu.configs.paper import build_duo_fleet
from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.obs.health import (DivergenceError,
                                                     Watchdog, WatchdogError)
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
from distributed_cluster_gpus_tpu.sim.replay import (
    ABORT_CONTEXT_FILE, ReplayError, load_abort_context, replay_abort,
    replay_run, write_abort_context)
from distributed_cluster_gpus_tpu.utils.checkpoint import (
    config_fingerprint, save_checkpoint)

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(scope="module")
def duo_fleet():
    return build_duo_fleet()


# run_sim.py flags that rebuild DUO_PARAMS exactly — the CLI replay path
# must regenerate the identical params (fingerprint-checked)
DUO_FLAGS = ["--algo", "default_policy", "--duration", "90",
             "--log-interval", "5", "--inf-mode", "poisson",
             "--inf-rate", "2", "--trn-mode", "poisson", "--trn-rate", "0.1",
             "--job-cap", "128", "--queue-cap", "256", "--seed", "11",
             "--obs"]


def duo_obs_params(fleet):
    a = run_sim.parse_args(DUO_FLAGS)
    params = run_sim.build_params(a)
    return run_sim.finalize_queue_cap(params, fleet, 1)


CHSAC_KW = dict(
    algo="chsac_af", duration=60.0, log_interval=5.0,
    inf_mode="poisson", inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
    job_cap=128, queue_cap=256, seed=11, rl_warmup=64, rl_batch=32,
)


# ---------------------------------------------------------------------------
# abort context io (quick)
# ---------------------------------------------------------------------------

def test_abort_context_roundtrip(tmp_path, duo_fleet):
    from distributed_cluster_gpus_tpu.rl.campaign import DivergenceConfig

    params = SimParams(**CHSAC_KW)
    err = DivergenceError("diverged", probe="critic_loss_max",
                          config=DivergenceConfig(critic_loss_max=10.0))
    b = str(tmp_path / "aborted")
    write_abort_context(b, error=err, chunk=7, chunk_steps=128,
                        fleet=duo_fleet, params=params,
                        trees=["sac", "replay", "key", "sim", "csv"],
                        train={"train_every_n": 1,
                               "max_train_steps_per_chunk": 256})
    ctx = load_abort_context(b)
    assert ctx["kind"] == "divergence"
    assert ctx["probes"] == ["critic_loss_max"]
    assert ctx["chunk"] == 7 and ctx["chunk_steps"] == 128
    assert ctx["divergence"]["critic_loss_max"] == 10.0
    assert ctx["params_fingerprint"] == config_fingerprint(duo_fleet, params)
    assert ctx["train"]["max_train_steps_per_chunk"] == 256

    wd_err = WatchdogError("trip", probes=["nonfinite_energy"])
    b2 = str(tmp_path / "ab2")
    write_abort_context(b2, error=wd_err, chunk=2, chunk_steps=64,
                        fleet=duo_fleet, params=params, trees=["sim"])
    ctx2 = load_abort_context(b2)
    assert ctx2["kind"] == "watchdog"
    assert ctx2["probes"] == ["nonfinite_energy"]
    assert ctx2["train"] is None

    # strict JSON on disk (NaN-free), and a non-bundle dir refuses
    json.load(open(os.path.join(b, ABORT_CONTEXT_FILE)))
    with pytest.raises(ReplayError, match="not a forensic abort bundle"):
        load_abort_context(str(tmp_path / "empty"))


def test_replay_refuses_mismatched_world(tmp_path, duo_fleet):
    """The fingerprint gate: replaying against different params is an
    error (a what-if replay must opt in with force=True)."""
    params = SimParams(**CHSAC_KW)
    err = WatchdogError("trip", probes=["nonfinite_energy"])
    b = str(tmp_path / "aborted")
    write_abort_context(b, error=err, chunk=0, chunk_steps=32,
                        fleet=duo_fleet, params=params, trees=["sim"])
    other = dataclasses.replace(params, seed=99)
    with pytest.raises(ReplayError, match="fingerprint mismatch"):
        replay_abort(duo_fleet, other, b)


# ---------------------------------------------------------------------------
# watchdog replay e2e (slow): fabricated corrupted-state bundle through the
# real engine, API + CLI
# ---------------------------------------------------------------------------

def test_watchdog_replay_reproduces_and_bisects(tmp_path, duo_fleet):
    """A NaN that was CHECKPOINTED (so the trip is a pure function of the
    restored state) aborts the next chunk; replay restores the healthy
    store, reproduces the identical probe at the identical chunk,
    byte-matches the forensic state, and bisects to a 1-step window
    (the corrupted energy integral trips the probe on every step)."""
    params = duo_obs_params(duo_fleet)
    engine = Engine(duo_fleet, params)
    state = init_state(jax.random.key(params.seed), duo_fleet, params,
                       workload=engine.workload)
    state, _ = engine.run_chunk(state, None, n_steps=128)  # healthy chunk 0

    # the corruption that gets checkpointed: a NaN energy integral
    # persists (energy accumulates), so chunk 1 trips nonfinite_energy
    energy = np.asarray(state.dc.energy_j).copy()
    energy[0] = np.nan
    state = dataclasses.replace(
        state, dc=dataclasses.replace(
            state.dc, energy_j=jnp.asarray(energy)))

    store = str(tmp_path / "ck")
    save_checkpoint(store, 0, sim=state)
    viol0 = np.asarray(state.telemetry.viol).copy()
    state, _ = engine.run_chunk(state, None, n_steps=128)  # tripping chunk 1

    wd = Watchdog(mode="raise", log=lambda m: None)
    wd.prime(viol0)
    with pytest.raises(WatchdogError) as ei:
        wd.check(np.asarray(state.telemetry.viol))
    err = ei.value
    assert err.probes == ("nonfinite_energy",)

    bundle = os.path.join(store, "aborted")
    save_checkpoint(bundle, 1, sim=state)
    write_abort_context(bundle, error=err, chunk=1, chunk_steps=128,
                        fleet=duo_fleet, params=params, trees=["sim"])

    report = replay_abort(duo_fleet, params, bundle, verbose=True)
    assert report["reproduced"]
    assert report["probes"] == ["nonfinite_energy"]
    assert report["restored_step"] == 0
    assert report["state_match"], report["state_mismatches"]
    assert report["window_steps"] == 1, \
        "a NaN energy integral trips on the first step of the chunk"

    # CLI smoke: same bundle through scripts/replay_abort.py, params
    # rebuilt from the run_sim flags (fingerprint must match), PASS line
    from scripts.replay_abort import main as replay_main

    out_json = str(tmp_path / "report.json")
    rc = replay_main([bundle, "--fleet", "duo", "--no-bisect",
                      "--json", out_json] + DUO_FLAGS)
    assert rc == 0
    doc = json.load(open(out_json))
    assert doc["reproduced"] and doc["probes"] == ["nonfinite_energy"]

    # a mangled fleet flag must be refused by the fingerprint gate
    rc_bad = replay_main([bundle, "--fleet", "single_dc", "--no-bisect"]
                         + DUO_FLAGS)
    assert rc_bad == 1


# ---------------------------------------------------------------------------
# divergence replay e2e (slow): real chsac training abort -> replay
# ---------------------------------------------------------------------------

def test_divergence_abort_replays_and_bisects(tmp_path, duo_fleet):
    """Forced divergence (an absurdly low critic-loss ceiling — a REAL
    threshold trip, so the replayed gate re-fires from the replayed
    metrics): the trainer abort writes the bundle; replay reproduces the
    same probe at the same chunk, byte-matches the full forensic
    pipeline state (sim + sac + replay + key), and minimizes the
    window."""
    from distributed_cluster_gpus_tpu.rl.campaign import (DivergenceConfig,
                                                          DivergenceMonitor)
    from distributed_cluster_gpus_tpu.rl.train import train_chsac

    params = SimParams(**CHSAC_KW)
    monitor = DivergenceMonitor(DivergenceConfig(critic_loss_max=1e-12))
    ck = str(tmp_path / "ck")
    with pytest.raises(DivergenceError):
        train_chsac(duo_fleet, params, out_dir=None, chunk_steps=128,
                    ckpt_dir=ck, ckpt_every_chunks=1, resume=False,
                    on_chunk=lambda c, s, h: monitor.check(
                        c, h[-1] if h else None))

    bundle = os.path.join(ck, "aborted")
    ctx = load_abort_context(bundle)
    assert ctx["kind"] == "divergence"
    assert ctx["probes"] == ["critic_loss_max"]
    assert ctx["divergence"]["critic_loss_max"] == 1e-12

    report = replay_abort(duo_fleet, params, bundle, verbose=True)
    assert report["reproduced"]
    assert report["probes"] == ["critic_loss_max"]
    assert report["chunk"] == ctx["chunk"]
    assert report["state_match"], report["state_mismatches"]
    assert 0 < report["window_steps"] <= 128
    # the minimal window needs enough rollout to fill the warmup and
    # train at least once — a 1-step window cannot trip this probe
    assert report["window_steps"] > 1


# ---------------------------------------------------------------------------
# clean replay (slow): CSV bytes reproduce from a mid-run checkpoint
# ---------------------------------------------------------------------------

def test_clean_replay_csv_byte_match(tmp_path, duo_fleet):
    """A healthy run replayed from a MID-RUN verified checkpoint into a
    fresh workspace reproduces the original CSVs byte-for-byte (the
    byte-watermark resume + deterministic engine close the loop)."""
    from distributed_cluster_gpus_tpu.rl.train import train_chsac

    params = SimParams(**{**CHSAC_KW, "duration": 90.0})
    full = str(tmp_path / "full")
    ck = str(tmp_path / "ck")
    train_chsac(duo_fleet, params, out_dir=full, chunk_steps=64,
                ckpt_dir=ck, ckpt_every_chunks=1, resume=False)
    from distributed_cluster_gpus_tpu.utils.checkpoint import steps

    all_steps = steps(ck)
    assert len(all_steps) >= 2, "need a mid-run checkpoint to replay from"
    mid = all_steps[len(all_steps) // 2 - 1] if len(all_steps) > 2 \
        else all_steps[0]

    rep = str(tmp_path / "replayed")
    replay_run(duo_fleet, params, ck, full, rep, step=mid,
               chunk_steps=64, ckpt_every_chunks=1)
    for name in ("cluster_log.csv", "job_log.csv"):
        with open(os.path.join(full, name), "rb") as f:
            want = f.read()
        with open(os.path.join(rep, name), "rb") as f:
            got = f.read()
        assert got == want, f"{name}: replayed bytes differ"
    # the evidence store was never mutated (the replay used its own copy)
    assert steps(ck) == all_steps

"""Evaluation harness: same-workload comparison + exact finished-units metric."""

import dataclasses

import numpy as np

from distributed_cluster_gpus_tpu.evaluation import baseline_config, compare, run_algo
from distributed_cluster_gpus_tpu.models import SimParams


def test_units_finished_tracks_job_sizes(single_dc_fleet, tmp_path):
    import pandas as pd

    from distributed_cluster_gpus_tpu.sim.io import run_simulation

    params = SimParams(algo="joint_nf", duration=40.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=2.0, trn_mode="off",
                       job_cap=128, seed=6)
    out = str(tmp_path / "r")
    state = run_simulation(single_dc_fleet, params, out_dir=out, chunk_steps=1024)
    jb = pd.read_csv(out + "/job_log.csv")
    np.testing.assert_allclose(float(np.asarray(state.units_finished)[0]),
                               jb["size"].sum(), rtol=1e-4)


def test_compare_same_workload_joint_nf_saves_energy(single_dc_fleet):
    base = SimParams(algo="default_policy", duration=60.0, log_interval=10.0,
                     inf_mode="poisson", inf_rate=3.0, trn_mode="off",
                     job_cap=256, seed=4)
    rows = compare(single_dc_fleet, base, ["default_policy", "joint_nf"],
                   chunk_steps=2048, verbose=False)
    by = {r.algo: r for r in rows}
    # the energy-optimal grid search must not use MORE energy per unit than
    # the fixed-frequency heuristic on the identical workload
    assert by["joint_nf"].energy_per_unit_wh < by["default_policy"].energy_per_unit_wh
    # and both served comparable load
    assert by["joint_nf"].completed_inf > 0.8 * by["default_policy"].completed_inf


def test_baseline_config_shapes():
    for n in (1, 2, 3, 4):
        spec = baseline_config(n, 60.0)
        assert spec["algos"]
        assert spec["base"].duration == 60.0
        for algo in spec["algos"]:
            dataclasses.replace(spec["base"], algo=algo)  # valid algo codes

"""Evaluation harness: same-workload comparison + exact finished-units metric."""

import dataclasses

import numpy as np
import pytest

from distributed_cluster_gpus_tpu.evaluation import baseline_config, compare, run_algo
from distributed_cluster_gpus_tpu.models import SimParams


def test_units_finished_tracks_job_sizes(single_dc_fleet, tmp_path):
    import pandas as pd

    from distributed_cluster_gpus_tpu.sim.io import run_simulation

    params = SimParams(algo="joint_nf", duration=40.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=2.0, trn_mode="off",
                       job_cap=128, seed=6)
    out = str(tmp_path / "r")
    state = run_simulation(single_dc_fleet, params, out_dir=out, chunk_steps=1024)
    jb = pd.read_csv(out + "/job_log.csv")
    np.testing.assert_allclose(float(np.asarray(state.units_finished)[0]),
                               jb["size"].sum(), rtol=1e-4)


def test_compare_same_workload_joint_nf_saves_energy(single_dc_fleet):
    base = SimParams(algo="default_policy", duration=60.0, log_interval=10.0,
                     inf_mode="poisson", inf_rate=3.0, trn_mode="off",
                     job_cap=256, seed=4)
    rows = compare(single_dc_fleet, base, ["default_policy", "joint_nf"],
                   chunk_steps=2048, verbose=False)
    by = {r.algo: r for r in rows}
    # the energy-optimal grid search must not use MORE energy per unit than
    # the fixed-frequency heuristic on the identical workload
    assert by["joint_nf"].energy_per_unit_wh < by["default_policy"].energy_per_unit_wh
    # and both served comparable load
    assert by["joint_nf"].completed_inf > 0.8 * by["default_policy"].completed_inf


def test_baseline_config_shapes():
    for n in (1, 2, 3, 4):
        spec = baseline_config(n, 60.0)
        assert spec["algos"]
        assert spec["base"].duration == 60.0
        for algo in spec["algos"]:
            dataclasses.replace(spec["base"], algo=algo)  # valid algo codes


def test_variant_3c_breaks_carbon_cost_degeneracy():
    """Under 3c (zero price) the CI=0 quirk cell diverges carbon_cost from
    joint_nf — in the paper world the two are identical by construction
    (price > 0 makes the cost score a monotone transform of energy)."""
    import math

    from distributed_cluster_gpus_tpu.evaluation import compare, variant_config

    spec = variant_config("3c", 60.0)
    rows = compare(spec["fleet"], spec["base"], ["joint_nf", "carbon_cost"],
                   chunk_steps=2048, verbose=False)
    r1, r2 = [s.row() for s in rows]
    assert r1["energy_kwh"] != r2["energy_kwh"]
    assert not math.isnan(r1["energy_kwh"])


def test_compare_seeds_aggregate_shape(single_dc_fleet):
    from distributed_cluster_gpus_tpu.evaluation import compare_seeds
    from distributed_cluster_gpus_tpu.models import SimParams

    base = SimParams(algo="joint_nf", duration=30.0, log_interval=10.0,
                     inf_mode="poisson", inf_rate=3.0, trn_mode="off",
                     job_cap=128)
    out = compare_seeds(single_dc_fleet, base, ["joint_nf", "default_policy"],
                        seeds=[7, 8], chunk_steps=1024, verbose=False)
    assert set(out) == {"per_seed", "aggregate", "run_shape"}
    assert len(out["per_seed"]) == 2 and len(out["aggregate"]) == 2
    assert out["run_shape"]["queue_mode"] == "ring"
    agg = out["aggregate"][0]
    assert agg["n_seeds"] == 2
    assert "energy_kwh_mean" in agg and "energy_kwh_sd" in agg
    # different seeds -> different workloads -> nonzero variance
    assert agg["energy_kwh_sd"] > 0


@pytest.mark.parametrize("variant", ["3s", "4s"])
def test_variant_steady_state_no_drops(variant):
    """3s/4s variants must not truncate the workload (dropped ~ 0)."""
    import dataclasses

    from distributed_cluster_gpus_tpu.evaluation import run_algo, variant_config

    spec = variant_config(variant, 120.0)
    s = run_algo(spec["fleet"],
                 dataclasses.replace(spec["base"], algo="joint_nf"),
                 chunk_steps=2048)
    assert s.dropped == 0

"""Fault-injection subsystem semantics (fault/ + engine EV_FAULT).

Covers the acceptance properties of the fault subsystem:
* zero-fault golden: an enabled-but-empty schedule is bit-identical to
  the fault-free engine (states AND csv bytes);
* outages preempt running work, zero the DC's capacity/power, and block
  any execution on the downed DC; energy/utilisation accrual is
  conserved across the window (flat while down, resumes after);
* preempted jobs migrate to surviving capacity (or fail when none
  exists) with progress preserved;
* recovery re-admits queued work in FIFO order;
* derate windows clamp job frequencies; WAN windows stretch transfer
  latencies;
* a vmapped batch of lanes with different stochastic keys realizes
  independent fault trajectories.
"""

import dataclasses
import filecmp

import jax
import numpy as np
import pandas as pd
import pytest

from distributed_cluster_gpus_tpu.configs.paper import build_duo_fleet
from distributed_cluster_gpus_tpu.models import FaultParams, SimParams
from distributed_cluster_gpus_tpu.sim.io import run_simulation


@pytest.fixture(scope="module")
def duo_fleet():
    """Tiny 2-DC world (fast compiles; enough topology for migration)."""
    return build_duo_fleet()


def run(fleet, tmp_path, name, **kw):
    params = SimParams(**kw)
    out = str(tmp_path / name)
    state = run_simulation(fleet, params, out_dir=out, chunk_steps=1024)
    cl = pd.read_csv(out + "/cluster_log.csv")
    jb = pd.read_csv(out + "/job_log.csv")
    return state, cl, jb, out


DUO_KW = dict(
    algo="default_policy", duration=90.0, log_interval=5.0,
    inf_mode="poisson", inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
    job_cap=128, queue_cap=256, seed=11,
)


def test_zero_fault_schedule_bit_identical(duo_fleet, tmp_path):
    """Acceptance golden: FaultParams() (enabled, empty timeline) must
    realize the exact run the fault-free engine produces — same PRNG
    consumption, same event order, byte-equal job log."""
    s0, cl0, _, out0 = run(duo_fleet, tmp_path, "off", **DUO_KW)
    s1, cl1, _, out1 = run(duo_fleet, tmp_path, "empty",
                           faults=FaultParams(), **DUO_KW)
    assert int(s0.n_events) == int(s1.n_events)
    np.testing.assert_array_equal(np.asarray(s0.dc.energy_j),
                                  np.asarray(s1.dc.energy_j))
    np.testing.assert_array_equal(np.asarray(s0.jobs.status),
                                  np.asarray(s1.jobs.status))
    np.testing.assert_array_equal(np.asarray(s0.n_finished),
                                  np.asarray(s1.n_finished))
    np.testing.assert_array_equal(np.asarray(s0.lat.buf),
                                  np.asarray(s1.lat.buf))
    assert filecmp.cmp(out0 + "/job_log.csv", out1 + "/job_log.csv",
                       shallow=False)
    # the fault run's cluster log carries two extra columns; the base
    # schema prefix must match the fault-free run exactly
    base_cols = list(cl0.columns)
    pd.testing.assert_frame_equal(cl1[base_cols], cl0)
    assert (cl1["up"] == 1).all()


@pytest.fixture(scope="module")
def outage_run(duo_fleet, tmp_path_factory):
    fp = FaultParams(outages=((0, 30.0, 60.0),))
    return run(duo_fleet, tmp_path_factory.mktemp("outage"), "outage",
               faults=fp, **DUO_KW)


def test_outage_blocks_execution_on_down_dc(duo_fleet, outage_run):
    state, cl, jb, _ = outage_run
    dc0 = duo_fleet.dc_names[0]
    d0 = cl[cl.dc == dc0]
    inside = d0[(d0.time_s > 30.0) & (d0.time_s < 60.0)]
    assert len(inside) >= 4
    assert (inside.up == 0).all()
    assert (inside.busy == 0).all()
    assert (inside.run_total == 0).all()
    assert (inside.power_W == 0).all()
    # no completed job executed on the downed DC inside the window
    on_dc0 = jb[jb.dc == dc0]
    bad = on_dc0[((on_dc0.start_s > 30.0) & (on_dc0.start_s < 60.0))
                 | ((on_dc0.finish_s > 30.0) & (on_dc0.finish_s < 60.0))]
    assert len(bad) == 0, bad


def test_outage_energy_and_util_conserved(duo_fleet, outage_run):
    """Energy integral is flat across the outage (no phantom accrual) and
    the downtime accounting matches the schedule exactly."""
    state, cl, _, _ = outage_run
    d0 = cl[cl.dc == duo_fleet.dc_names[0]]
    # energy at every tick strictly inside the window equals the value at
    # the first inside tick (nothing runs, idle floor is powered off)
    inside = d0[(d0.time_s > 30.0) & (d0.time_s <= 60.0)]
    assert inside.energy_kJ.nunique() == 1
    # energy resumes accruing after recovery
    after = d0[d0.time_s > 65.0]
    assert after.energy_kJ.max() > inside.energy_kJ.max()
    # downtime integral == realized window length
    np.testing.assert_allclose(float(np.asarray(state.fault.downtime)[0]),
                               30.0, atol=0.5)
    assert int(np.asarray(state.fault.n_outages)[0]) == 1
    # util_avg never exceeds 1 despite the capacity hole
    assert (cl.util_avg <= 1.0 + 1e-6).all()


def test_outage_migrates_running_jobs(outage_run):
    """Jobs running at onset are preempted and re-homed to the up DC (the
    fleet always has one), never failed — and never left stranded
    PREEMPTED or parked QUEUED at an idle DC (the migration step promotes
    a drain at its target)."""
    from distributed_cluster_gpus_tpu.models import JobStatus

    state, _, _, _ = outage_run
    fs = state.fault
    assert int(fs.n_preempted) >= 1
    assert int(fs.n_migrated) >= 1
    assert int(fs.n_failed) == 0
    assert int(fs.n_migrated) <= int(fs.n_preempted)
    assert not (np.asarray(state.jobs.status) == JobStatus.PREEMPTED).any()


def test_flash_outage_leaves_no_stranded_jobs(duo_fleet, tmp_path):
    """A near-instant outage recovers before the bounded migration drain
    reaches the preempted rows; they must still be re-queued and finish —
    under the heuristic algorithms nothing else consumes PREEMPTED, so a
    row left behind would leak its slab slot forever."""
    from distributed_cluster_gpus_tpu.models import JobStatus

    fp = FaultParams(outages=((0, 30.0, 30.001),))
    state, _, _, _ = run(duo_fleet, tmp_path, "flash", faults=fp, **DUO_KW)
    fs = state.fault
    assert int(fs.n_preempted) >= 1
    assert int(fs.n_failed) == 0
    # no stranded PREEMPTED rows at end of run
    assert not (np.asarray(state.jobs.status) == JobStatus.PREEMPTED).any()


def test_total_blackout_fails_unplaceable_jobs(duo_fleet, tmp_path):
    """With EVERY DC down, preempted jobs have nowhere to go: they are
    dropped and counted in n_failed (the no-capacity outcome)."""
    fp = FaultParams(outages=((0, 30.0, 60.0), (1, 30.0, 60.0)))
    state, cl, _, _ = run(duo_fleet, tmp_path, "blackout", faults=fp,
                          **DUO_KW)
    fs = state.fault
    assert int(fs.n_preempted) >= 1
    assert int(fs.n_failed) >= 1
    assert int(fs.n_migrated) + int(fs.n_failed) <= int(fs.n_preempted)
    # both DCs show zero capacity inside the window
    inside = cl[(cl.time_s > 30.0) & (cl.time_s < 60.0)]
    assert (inside.busy == 0).all()
    assert (inside.up == 0).all()


def test_outage_migration_slab_queue_mode(duo_fleet, tmp_path):
    """The slab queue layout routes fault migration through QUEUED rows
    instead of ring pushes — same preempt/migrate accounting."""
    fp = FaultParams(outages=((0, 30.0, 60.0),))
    kw = dict(DUO_KW, queue_mode="slab")
    state, cl, jb, _ = run(duo_fleet, tmp_path, "slab", faults=fp, **kw)
    fs = state.fault
    assert int(fs.n_preempted) >= 1
    assert int(fs.n_migrated) >= 1
    assert int(fs.n_failed) == 0
    d0 = cl[cl.dc == duo_fleet.dc_names[0]]
    inside = d0[(d0.time_s > 30.0) & (d0.time_s < 60.0)]
    assert (inside.busy == 0).all()


def test_overlapping_outages_nest(duo_fleet, tmp_path):
    """Overlapping outage windows on one DC nest via the depth counter:
    the inner window's recovery must not restore the DC while the outer
    window is still open, and the merged incident counts once."""
    fp = FaultParams(outages=((0, 20.0, 70.0), (0, 30.0, 40.0)))
    state, cl, _, _ = run(duo_fleet, tmp_path, "nest", faults=fp, **DUO_KW)
    d0 = cl[cl.dc == duo_fleet.dc_names[0]]
    # after the INNER window's up-event the DC must still be dark
    inside = d0[(d0.time_s > 40.0) & (d0.time_s < 70.0)]
    assert len(inside) >= 4
    assert (inside.up == 0).all()
    assert (inside.busy == 0).all()
    after = d0[d0.time_s > 72.0]
    assert (after.up == 1).all()
    fs = state.fault
    assert int(np.asarray(fs.n_outages)[0]) == 1  # one merged incident
    np.testing.assert_allclose(float(np.asarray(fs.downtime)[0]), 50.0,
                               atol=0.5)


def test_fault_spec_validation():
    """Spec-time rejection of malformed/overlapping windows and
    out-of-range fleet indices (stateless derate/WAN resets cannot nest)."""
    import jax.numpy as jnp

    from distributed_cluster_gpus_tpu.fault.schedule import init_fault_state

    with pytest.raises(ValueError, match="end <= start"):
        FaultParams(outages=((0, 20.0, 10.0),))
    with pytest.raises(ValueError, match="overlapping derate"):
        FaultParams(derates=((0, 0.0, 50.0, 0.5), (0, 30.0, 60.0, 0.6)))
    with pytest.raises(ValueError, match="overlapping wan"):
        FaultParams(wan=((0, 0, 0.0, 50.0, 2.0, 0.0),
                         (0, 0, 10.0, 20.0, 3.0, 0.0)))
    with pytest.raises(ValueError, match="out of range"):
        init_fault_state(
            jax.random.key(0), FaultParams(outages=((9, 0.0, 1.0),)),
            n_dc=2, n_ing=2, freq_levels=np.linspace(0.3, 1.0, 8),
            tdtype=jnp.float32)


def test_recovery_readmits_fifo(single_dc_fleet, tmp_path):
    """Arrivals that queue behind an outage start in FIFO (jid) order once
    the DC recovers, with progress-free fresh starts at/after recovery."""
    fp = FaultParams(outages=((0, 10.0, 50.0),))
    state, cl, jb, _ = run(
        single_dc_fleet, tmp_path, "recovery", faults=fp,
        algo="default_policy", duration=120.0, log_interval=5.0,
        inf_mode="poisson", inf_rate=2.0, trn_mode="off",
        job_cap=128, queue_cap=256, seed=3)
    # nothing STARTS inside the outage window (the DC reports 0 capacity)
    started_inside = jb[(jb.start_s > 10.0) & (jb.start_s < 50.0)]
    assert len(started_inside) == 0
    # the recovery event drains the queue heads at exactly t=50; every job
    # with a smaller jid that also starts at/after 50 was therefore queued
    # at recovery (jid == arrival order), and FIFO re-admission means this
    # queued-at-recovery cohort starts in jid order.  (Jobs arriving AFTER
    # recovery may legally start ahead of the backlog when GPUs are free —
    # the engine admits at xfer-completion without consulting the queue —
    # so the cohort, not the full post-50 set, carries the ordering.)
    burst = jb[np.isclose(jb.start_s, 50.0, atol=1e-6)]
    assert len(burst) >= 2, "recovery drain should start the queue heads"
    cohort = jb[(jb.start_s >= 50.0)
                & (jb.jid <= burst.jid.max())].sort_values("jid")
    assert len(cohort) >= len(burst)
    assert (np.diff(cohort.start_s.to_numpy()) >= -1e-6).all()
    assert int(np.asarray(state.n_finished)[0]) == len(jb)


def test_derate_clamps_frequencies(single_dc_fleet, tmp_path):
    """A straggler window caps f_used for jobs started inside it; after
    the window new starts use the full ladder again."""
    fp = FaultParams(derates=((0, 0.0, 60.0, 0.5),))
    _, _, jb, _ = run(
        single_dc_fleet, tmp_path, "derate", faults=fp,
        algo="debug", duration=120.0, log_interval=5.0,
        inf_mode="poisson", inf_rate=2.0, trn_mode="off",
        num_fixed_gpus=1, fixed_freq=1.0, job_cap=128, queue_cap=256,
        seed=5)
    during = jb[(jb.start_s > 0.0) & (jb.start_s < 60.0)]
    after = jb[jb.start_s >= 60.0]
    assert len(during) > 20 and len(after) > 20
    np.testing.assert_allclose(during.f_used, 0.5, atol=1e-6)
    np.testing.assert_allclose(after.f_used, 1.0, atol=1e-6)
    # derated jobs run slower: T(1, 0.5) > T(1, 1.0)
    assert during.T_pred.mean() > after.T_pred.mean()


def test_wan_degradation_stretches_latency(single_dc_fleet, tmp_path):
    """A WAN window multiplies the edge's propagation latency by
    lat_mult / (1 - loss) for arrivals routed through it."""
    from distributed_cluster_gpus_tpu.network import loss_latency_multiplier

    mult, loss = 3.0, 0.2
    fp = FaultParams(wan=((0, 0, 0.0, 60.0, mult, loss),))
    _, _, jb, _ = run(
        single_dc_fleet, tmp_path, "wan", faults=fp,
        algo="debug", duration=120.0, log_interval=5.0,
        inf_mode="poisson", inf_rate=2.0, trn_mode="off",
        num_fixed_gpus=1, fixed_freq=1.0, job_cap=128, queue_cap=256,
        seed=5)
    base_lat = float(single_dc_fleet.net_lat_s[0, 0])
    eff = mult * loss_latency_multiplier(loss)
    # net_lat_s is stamped at arrival: early arrivals see the degraded
    # edge, late arrivals the healthy one.  (The window closes at t=60;
    # arrivals land before their transfer completes, so split well clear
    # of the boundary.)
    early = jb[jb.finish_s < 55.0]
    late = jb[jb.start_s > 70.0]
    assert len(early) > 10 and len(late) > 10
    np.testing.assert_allclose(early.net_lat_s, base_lat * eff, rtol=1e-4)
    np.testing.assert_allclose(late.net_lat_s, base_lat, rtol=1e-4)


def test_apply_wan_degradation_matches_engine_semantics(duo_fleet):
    """The host-side what-if helper applies the same per-edge stretch the
    engine applies at its transfer-stamping sites: latency rows scale by
    mult, transfer rows by the same mult across both payload classes."""
    from distributed_cluster_gpus_tpu.network import (
        apply_wan_degradation, loss_latency_multiplier)

    mats = {"net_lat_s": np.asarray(duo_fleet.net_lat_s),
            "transfer_s": np.asarray(duo_fleet.transfer_s)}
    mult = np.ones_like(mats["net_lat_s"])
    eff = 2.0 * loss_latency_multiplier(0.5)  # = 4.0
    mult[0, 1] = eff
    out = apply_wan_degradation(mats, mult)
    np.testing.assert_allclose(out["net_lat_s"][0, 1],
                               mats["net_lat_s"][0, 1] * eff)
    np.testing.assert_allclose(out["transfer_s"][0, 1],
                               mats["transfer_s"][0, 1] * eff)
    # untouched edges pass through exactly
    np.testing.assert_array_equal(out["net_lat_s"][1], mats["net_lat_s"][1])
    np.testing.assert_array_equal(out["transfer_s"][1], mats["transfer_s"][1])


def test_vmapped_stochastic_schedules_independent(duo_fleet):
    """batched_init lanes fold distinct keys into the fault sampler, so a
    vmapped run realizes independent outage trajectories per lane."""
    from distributed_cluster_gpus_tpu.parallel.rollout import batched_init
    from distributed_cluster_gpus_tpu.sim.engine import Engine

    fp = FaultParams(mtbf_s=60.0, mttr_s=30.0, max_outages_per_dc=3)
    params = SimParams(**dict(DUO_KW, duration=200.0), faults=fp)
    states = batched_init(duo_fleet, params, n_rollouts=3)
    times = np.asarray(states.fault.times)
    assert times.shape[0] == 3
    # independent draws: no two lanes share a timeline
    assert not np.array_equal(times[0], times[1])
    assert not np.array_equal(times[1], times[2])

    eng = Engine(duo_fleet, params)
    run_v = jax.jit(jax.vmap(lambda s: eng._run_chunk(s, None, 512)))
    out, _ = run_v(states)
    assert (np.asarray(out.n_events) > 0).all()
    down = np.asarray(out.fault.downtime)  # [3, n_dc]
    # each lane accrued downtime from ITS schedule, not a shared one
    assert not np.allclose(down[0], down[1])
    # at least one lane's outage fired within the chunk horizon (a lane
    # whose first Exp(mtbf) draw lies beyond the reached t legally stays
    # at cursor 0 — independence, not a bug)
    cursors = np.asarray(out.fault.cursor)
    assert (cursors > 0).any()


def test_fault_metrics_summary(duo_fleet, outage_run):
    """evaluation.fault_metrics reports availability, recovery time, and
    the migration counters for a fault run (and {} for a fault-free one)."""
    from distributed_cluster_gpus_tpu.evaluation import fault_metrics

    state = outage_run[0]
    m = fault_metrics(duo_fleet, state)
    # one 16-GPU DC of 32 total down for 30 s of 90 s: ~1/6 capacity loss
    assert 0.75 < m["availability"] < 0.9
    np.testing.assert_allclose(m["mean_recovery_s"], 30.0, atol=0.5)
    assert m["n_outages"] == 1
    assert m["n_fault_preempted"] >= 1
    assert m["n_fault_migrated"] >= 1
    assert m["n_fault_failed"] == 0


def test_chsac_elastic_respects_outage(duo_fleet):
    """The RL engine (policy tail, masks, elastic machinery) honors the
    capacity mask: nothing runs on the downed DC, and the run proceeds
    through the outage without losing accounting consistency."""
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import (
        SACConfig, make_policy_apply, sac_init)
    from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
    from distributed_cluster_gpus_tpu.models import JobStatus

    fp = FaultParams(outages=((0, 20.0, 70.0),))
    params = SimParams(
        algo="chsac_af", duration=100.0, log_interval=5.0,
        inf_mode="poisson", inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
        elastic_scaling=True, job_cap=96, queue_cap=256, lat_window=256,
        seed=2, faults=fp)
    cfg = SACConfig(obs_dim=params.obs_dim(duo_fleet.n_dc),
                    n_dc=duo_fleet.n_dc, n_g=params.max_gpus_per_job,
                    constraints=default_constraints(500.0))
    sac = sac_init(cfg, jax.random.key(1))
    eng = Engine(duo_fleet, params, policy_apply=make_policy_apply(cfg))
    state = init_state(jax.random.key(0), duo_fleet, params)
    for _ in range(8):
        state, _ = eng.run_chunk(state, sac, n_steps=512)
        jobs = state.jobs
        running0 = (np.asarray(jobs.status) == JobStatus.RUNNING) \
            & (np.asarray(jobs.dc) == 0)
        t = float(state.t)
        if 20.0 < t <= 70.0 and not bool(np.asarray(state.fault.dc_up)[0]):
            assert not running0.any()
            assert int(np.asarray(state.dc.busy)[0]) == 0
        if bool(state.done):
            break
    assert bool(state.done)
    assert int(state.n_events) > 0
    assert int(np.asarray(state.fault.n_outages)[0]) == 1

"""obs/ subsystem semantics: telemetry, exporters, watchdog, tracing.

Covers the acceptance properties of the observability layer:
* compile-gating: with ``obs_enabled=False`` the traced program is
  byte-identical no matter what the obs shape knobs say, and no obs
  emission keys exist;
* an obs-enabled run leaves cluster_log.csv / job_log.csv bytes
  unchanged (K=1 and the K=4 superstep);
* the in-graph probes catch a seeded NaN and a forced ring overflow,
  and the host watchdog warns/raises per its mode;
* exporter output round-trips: the Prometheus snapshot and the JSONL
  stream parse back to the registry layout, and run_summary.json's
  totals match `evaluation._summarize` exactly;
* the metric registry passes the schema linter
  (scripts/check_metrics_schema.py) — unique names, stable ids,
  declared units;
* PhaseTimer spans export as Perfetto-loadable chrome-trace JSON.
"""

import dataclasses
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.configs.paper import build_duo_fleet
from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.obs.export import ObsConfig
from distributed_cluster_gpus_tpu.obs.health import (
    HARD_PROBES, N_PROBES, P_JOB_CONSERVATION, P_NONFINITE_ENERGY,
    P_NONFINITE_POWER, P_RING_FULL, P_RING_NEGATIVE, P_RING_OVERFLOW,
    PROBE_NAMES, Watchdog, WatchdogError, probe_step, split_counts)
from distributed_cluster_gpus_tpu.obs.metrics import (
    METRIC_TABLE, registry_for, registry_width)
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
from distributed_cluster_gpus_tpu.sim.io import run_simulation

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(scope="module")
def duo_fleet():
    """Tiny 2-DC world (fast compiles, same shape the fault suite uses)."""
    return build_duo_fleet()


DUO_KW = dict(
    algo="default_policy", duration=90.0, log_interval=5.0,
    inf_mode="poisson", inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
    job_cap=128, queue_cap=256, seed=11,
)


@pytest.fixture(scope="module", params=[1, 4], ids=["k1", "k4"])
def obs_pair(request, duo_fleet, tmp_path_factory):
    """One obs-off and one obs-on run of the same config; shared by the
    byte-identity, exporter, and summary tests."""
    k = request.param
    out = {}
    for obs in (False, True):
        params = SimParams(superstep_k=k, obs_enabled=obs, **DUO_KW)
        d = str(tmp_path_factory.mktemp(f"obs_{k}_{obs}"))
        state = run_simulation(
            duo_fleet, params, out_dir=d, chunk_steps=512,
            obs=ObsConfig(out_dir=d, watchdog="off") if obs else None)
        out[obs] = (params, d, state)
    return out


# ---------------------------------------------------------------------------
# compile-gating
# ---------------------------------------------------------------------------

def test_obs_off_program_gating_complete(duo_fleet):
    """With obs_enabled=False the obs shape knobs must not leak into the
    traced program (same jaxpr bytes), the state carries no telemetry,
    and the emission stream has no obs keys."""
    def trace(**kw):
        params = SimParams(**DUO_KW, **kw)
        eng = Engine(duo_fleet, params)
        st = init_state(jax.random.key(0), duo_fleet, params)
        jpr = jax.make_jaxpr(lambda s: eng._run_chunk(s, None, 8))(st)
        return params, st, jpr

    _, st0, jpr0 = trace()
    _, _, jpr1 = trace(obs_ema_alpha=0.5, obs_qdepth_bins=16)
    assert str(jpr0) == str(jpr1), (
        "obs_* knobs changed the obs-off program — the compile gate leaks")
    assert st0.telemetry is None
    params, st, _ = trace()
    eng = Engine(duo_fleet, params)
    _, em = jax.eval_shape(lambda s: eng._run_chunk(s, None, 8), st)
    assert not any(k.startswith("obs") for k in em), sorted(em)


def test_obs_params_validated():
    with pytest.raises(ValueError, match="obs_ema_alpha"):
        SimParams(**DUO_KW, obs_ema_alpha=0.0)
    with pytest.raises(ValueError, match="obs_qdepth_bins"):
        SimParams(**DUO_KW, obs_qdepth_bins=1)


# ---------------------------------------------------------------------------
# byte-identity + exporters (shared runs)
# ---------------------------------------------------------------------------

def test_obs_on_csv_bytes_unchanged(obs_pair):
    _, d_off, _ = obs_pair[False]
    _, d_on, _ = obs_pair[True]
    for f in ("cluster_log.csv", "job_log.csv"):
        with open(os.path.join(d_off, f), "rb") as a, \
                open(os.path.join(d_on, f), "rb") as b:
            assert a.read() == b.read(), (
                f"{f} differs with obs_enabled=True — telemetry must be "
                "emission-only, never touching the reference log path")


def test_obs_artifacts_written_and_parse(obs_pair, duo_fleet):
    params, d, state = obs_pair[True]
    width = registry_width(registry_for(duo_fleet, params))
    # jsonl: one record per log tick, every registry metric present
    recs = [json.loads(line)
            for line in open(os.path.join(d, "metrics.jsonl"))]
    assert recs, "empty metrics.jsonl"
    names = {s.name for s in METRIC_TABLE
             if not s.fault_only and not s.signal_only}
    for rec in recs:
        assert names <= set(rec), names - set(rec)
    # monotone sim time and counters
    ts = [r["t"] for r in recs]
    assert ts == sorted(ts)
    ev = [r["obs_events_total"] for r in recs]
    assert ev == sorted(ev)
    assert recs[-1]["obs_events_total"] <= int(state.n_events)
    # prometheus snapshot: parses, sample count == registry width plus
    # the export-derived gauges (obs_superstep_fill — round 14; derived
    # at export so the step program and its eqn ceilings are untouched)
    prom = open(os.path.join(d, "metrics.prom")).read()
    samples = [line for line in prom.splitlines()
               if line and not line.startswith("#")]
    assert len(samples) == width + 1
    for line in samples:
        name_lab, val = line.rsplit(" ", 1)
        float(val)
        assert name_lab.startswith("dcg_obs_")
    fill = [float(line.rsplit(" ", 1)[1]) for line in samples
            if line.startswith("dcg_obs_superstep_fill")]
    assert len(fill) == 1 and 0.0 < fill[0] <= 1.0
    # the jsonl stream carries the same derived value per tick
    assert recs[-1]["obs_superstep_fill"] == pytest.approx(
        fill[0], abs=1e-6)


def test_prometheus_snapshot_matches_last_jsonl_record(obs_pair):
    _, d, _ = obs_pair[True]
    last = json.loads(open(os.path.join(d, "metrics.jsonl"))
                      .readlines()[-1])
    prom = {}
    for line in open(os.path.join(d, "metrics.prom")):
        if line.startswith("#") or not line.strip():
            continue
        name_lab, val = line.rsplit(" ", 1)
        name = name_lab.split("{")[0]
        prom.setdefault(name, []).append(float(val))
    for spec in METRIC_TABLE:
        if spec.fault_only or spec.signal_only:
            continue
        v = last[spec.name]
        v = v if isinstance(v, list) else [v]
        got = prom[f"dcg_{spec.name}"]
        assert got == pytest.approx(v, rel=1e-6, abs=1e-6), spec.name


def test_run_summary_totals_match_evaluation(obs_pair, duo_fleet):
    from distributed_cluster_gpus_tpu.evaluation import _summarize

    params, d, state = obs_pair[True]
    summary = json.load(open(os.path.join(d, "run_summary.json")))
    assert summary["schema"] == "dcg.run_summary.v1"
    assert summary["n_events"] == int(state.n_events)
    # the summary's totals are produced by evaluation._summarize itself;
    # re-derive from the final state and compare EXACTLY (a perf gate
    # diffs these against eval artifacts)
    want = _summarize(params.algo, duo_fleet, state).row()
    got = summary["totals"]
    for key, w in want.items():
        g = got[key]
        if isinstance(w, float) and np.isnan(w):
            assert g is None, key  # strict JSON: NaN -> null
        else:
            assert g == w, (key, g, w)
    # final snapshot metrics agree with the final state counters
    fm = summary["final_metrics"]
    assert fm["obs_dropped_total"] == float(np.asarray(state.n_dropped))
    assert fm["obs_finished_total"] == pytest.approx(
        np.asarray(state.n_finished).astype(float).tolist())
    # host-phase wall seconds are first-class fields (round 14): the
    # pipelined loop's dispatch/rollout/io split plus the background
    # workers' hidden render time, so the perf ledger can attribute
    # wall time per RUN, not just per bench probe
    hp = summary["host_phases"]
    for key in ("dispatch_s", "rollout_s", "io_s", "io_render_s",
                "obs_render_s"):
        assert key in hp and hp[key] >= 0.0, (key, hp)
    # superstep window fill derives from the final cumulative hist_l.
    # `fill` counts ALL iterations in the denominator (the bench
    # sweep's events_per_iteration / K — one definition across bench,
    # ledger, and run_summary); `mean_l` is the fired-only window-
    # quality mean (exactly 1.0 at K=1: a fired window applies 1 event)
    sf = summary["superstep"]
    assert sf["k"] == params.superstep_k
    assert 0.0 < sf["fill"] <= 1.0
    assert sf["iterations"] >= sf["fired"] > 0
    hist = np.asarray(state.telemetry.hist_l, dtype=float)
    applied = (np.arange(len(hist)) * hist).sum()
    assert sf["fill"] == pytest.approx(
        applied / hist.sum() / sf["k"], abs=1e-4)
    assert sf["mean_l"] == pytest.approx(
        applied / hist[1:].sum(), abs=1e-4)
    if params.superstep_k == 1:
        assert sf["mean_l"] == 1.0


def test_watchdog_zero_violations_on_clean_run(obs_pair):
    _, _, state = obs_pair[True]
    rep = split_counts(np.asarray(state.telemetry.viol))
    assert rep.violation_total == 0, rep.violations


# ---------------------------------------------------------------------------
# probes + watchdog
# ---------------------------------------------------------------------------

def _clean_probe_kw():
    return dict(
        powers=jnp.ones((2,), jnp.float32), energy_j=jnp.ones((2,)),
        t=jnp.float32(1.0), ring_cnt=jnp.array([[1, 0], [2, 3]]),
        ring_cap=8, arrived=jnp.int32(10), placed=jnp.int32(4),
        ring_queued=jnp.int32(6), finished=jnp.int32(0),
        dropped=jnp.int32(0), failed=jnp.int32(0), job_cap=16)


def test_probe_step_clean_is_silent():
    assert np.asarray(probe_step(**_clean_probe_kw())).tolist() == [0] * N_PROBES


@pytest.mark.parametrize("mutate, idx", [
    (dict(powers=jnp.array([1.0, jnp.nan], jnp.float32)), P_NONFINITE_POWER),
    (dict(energy_j=jnp.array([jnp.inf, 0.0])), P_NONFINITE_ENERGY),
    (dict(t=jnp.float32(jnp.nan)), P_NONFINITE_ENERGY),
    (dict(ring_cnt=jnp.array([[1, -1], [0, 0]]), ring_queued=jnp.int32(0),
          placed=jnp.int32(10)), P_RING_NEGATIVE),
    (dict(ring_cnt=jnp.array([[9, 0], [0, 0]]), ring_queued=jnp.int32(9),
          placed=jnp.int32(1)), P_RING_OVERFLOW),
    (dict(arrived=jnp.int32(11)), P_JOB_CONSERVATION),
    (dict(ring_cnt=jnp.array([[8, 0], [0, 0]]), ring_queued=jnp.int32(8),
          placed=jnp.int32(2)), P_RING_FULL),
], ids=["nan_power", "inf_energy", "nan_clock", "ring_negative",
        "ring_overflow", "conservation", "ring_full"])
def test_probe_step_trips(mutate, idx):
    kw = _clean_probe_kw()
    kw.update(mutate)
    v = np.asarray(probe_step(**kw))
    assert v[idx] == 1, (PROBE_NAMES[idx], v.tolist())


def test_engine_probe_catches_seeded_nan(duo_fleet):
    """Integration: corrupt the energy accumulator of a live state and the
    in-graph probe battery reports it through TelemetryState.viol."""
    params = SimParams(obs_enabled=True, **DUO_KW)
    eng = Engine(duo_fleet, params)
    st = init_state(jax.random.key(0), duo_fleet, params)
    st = st.replace(dc=st.dc.replace(
        energy_j=st.dc.energy_j.at[0].set(jnp.nan)))
    st, _ = eng.run_chunk(st, None, n_steps=32)
    viol = np.asarray(st.telemetry.viol)
    assert viol[P_NONFINITE_ENERGY] > 0, viol.tolist()


def test_engine_probe_catches_forced_ring_overflow(duo_fleet):
    """Integration: push a queue-ring tail past its capacity and the
    overflow probe trips every subsequent step."""
    params = SimParams(obs_enabled=True, **DUO_KW)
    eng = Engine(duo_fleet, params)
    st = init_state(jax.random.key(0), duo_fleet, params)
    cap = st.queues.recs.shape[2]
    st = st.replace(queues=st.queues.replace(
        tail=st.queues.tail.at[0, 0].set(st.queues.head[0, 0] + cap + 1)))
    st, _ = eng.run_chunk(st, None, n_steps=32)
    viol = np.asarray(st.telemetry.viol)
    assert viol[P_RING_OVERFLOW] > 0, viol.tolist()


def test_ring_pressure_counted_under_saturation(duo_fleet, tmp_path):
    """A deliberately starved ring (queue_cap=4 under the same workload)
    must register ring_full pressure steps — the chaos/forced-pressure
    acceptance row — while staying violation-free."""
    params = SimParams(obs_enabled=True,
                       **{**DUO_KW, "queue_cap": 4, "duration": 60.0})
    state = run_simulation(duo_fleet, params, out_dir=None, chunk_steps=512)
    rep = split_counts(np.asarray(state.telemetry.viol))
    assert rep.violation_total == 0, rep.violations
    assert rep.pressure["ring_full"] > 0, rep.pressure


def test_watchdog_modes():
    clean = np.zeros(N_PROBES, np.int64)
    hard = clean.copy()
    hard[HARD_PROBES[0]] = 2
    press = clean.copy()
    press[P_RING_FULL] = 7

    msgs = []
    w = Watchdog(mode="warn", log=msgs.append)
    w.check(clean)
    assert not msgs
    w.check(press)
    assert len(msgs) == 1 and "pressure" in msgs[0]
    rep = w.check(hard + press)  # cumulative totals, new hard trip
    assert any("INVARIANT" in m for m in msgs)
    assert rep.violation_total == 2 and rep.pressure_total == 7

    r = Watchdog(mode="raise", log=msgs.append)
    r.check(press)  # pressure never raises
    with pytest.raises(WatchdogError):
        r.check(hard + press)
    # no NEW trips since the last check -> no second raise
    Watchdog(mode="off", log=msgs.append).check(hard)

    with pytest.raises(ValueError):
        Watchdog(mode="panic")


def test_watchdog_reports_only_new_trips():
    msgs = []
    w = Watchdog(mode="warn", log=msgs.append)
    v = np.zeros(N_PROBES, np.int64)
    v[P_RING_FULL] = 3
    w.check(v)
    w.check(v)  # unchanged totals -> silent
    assert len(msgs) == 1


def test_watchdog_primed_baseline_skips_restored_history():
    # a resumed run restores cumulative viol counters from the checkpoint:
    # priming the baseline must keep historical trips from re-reporting
    # (or re-aborting in raise mode); only post-resume increments count
    restored = np.zeros(N_PROBES, np.int64)
    restored[HARD_PROBES[0]] = 5
    restored[P_RING_FULL] = 9

    msgs = []
    w = Watchdog(mode="raise", log=msgs.append)
    w.prime(restored)
    rep = w.check(restored)  # first post-resume chunk, nothing new
    assert not msgs
    assert rep.violation_total == 5  # totals still report the full history
    grown = restored.copy()
    grown[HARD_PROBES[0]] += 1
    with pytest.raises(WatchdogError):  # a genuinely NEW trip still raises
        w.check(grown)
    assert "+1" in msgs[-1] and "total 6" in msgs[-1]


def test_open_sink_primes_from_restored_state(obs_pair, duo_fleet, tmp_path):
    # ObsSink.open (the construction path run_simulation and the trainers
    # share) must prime the watchdog from the state it is handed
    from distributed_cluster_gpus_tpu.obs.export import ObsSink

    fleet = duo_fleet
    params, _, state = obs_pair[True]
    viol = np.asarray(state.telemetry.viol).copy()
    viol[HARD_PROBES[0]] = 3
    restored = state.replace(telemetry=state.telemetry.replace(
        viol=jnp.asarray(viol)))
    sink = ObsSink.open(
        ObsConfig(out_dir=str(tmp_path), watchdog="raise"),
        fleet=fleet, params=params, state=restored)
    try:
        sink.check(viol)  # restored history is the baseline -> no raise
    finally:
        sink.close(abort=True)


# ---------------------------------------------------------------------------
# schema linter (CI satellite: the registry contract is a tier-1 gate)
# ---------------------------------------------------------------------------

def test_metrics_schema_lint():
    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema",
        os.path.join(HERE, "..", "scripts", "check_metrics_schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.lint_table() == []


def test_metrics_schema_lint_catches_violations(monkeypatch):
    """The linter must actually fail on a broken table (id hole, bad
    unit), not just vacuously pass the good one."""
    import distributed_cluster_gpus_tpu.obs.metrics as m

    spec = importlib.util.spec_from_file_location(
        "check_metrics_schema_neg",
        os.path.join(HERE, "..", "scripts", "check_metrics_schema.py"))
    linter = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(linter)
    bad = (dataclasses.replace(m.METRIC_TABLE[0], mid=5),) + m.METRIC_TABLE[1:]
    monkeypatch.setattr(m, "METRIC_TABLE", bad)
    errs = linter.lint_table()
    assert any("contiguous" in e for e in errs)
    bad = (dataclasses.replace(m.METRIC_TABLE[0], unit="furlongs"),) \
        + m.METRIC_TABLE[1:]
    monkeypatch.setattr(m, "METRIC_TABLE", bad)
    assert any("undeclared unit" in e for e in linter.lint_table())


# ---------------------------------------------------------------------------
# metrics.jsonl checkpoint resume (round 9: the PR 4 truncation caveat fix)
# ---------------------------------------------------------------------------

def test_metrics_jsonl_resume_roundtrip(duo_fleet, tmp_path):
    """A checkpoint-resumed run APPENDS to metrics.jsonl from the
    restored tick — same byte-watermark semantics the CSVs have had
    since the checkpoint layer landed — instead of truncating the
    stream (the documented PR 4 caveat).  Round-trip golden: an
    interrupted+resumed chsac training run must reproduce the
    uninterrupted run's metrics.jsonl byte-for-byte, including dropping
    rows a crashed run wrote past its last checkpoint."""
    from distributed_cluster_gpus_tpu.rl.train import train_chsac

    def params():
        return SimParams(
            algo="chsac_af", duration=60.0, log_interval=5.0,
            inf_mode="poisson", inf_rate=3.0, trn_mode="off",
            rl_warmup=32, rl_batch=32, job_cap=128, seed=11,
            obs_enabled=True)

    kw = dict(chunk_steps=512, max_train_steps_per_chunk=8,
              ckpt_every_chunks=1)

    # golden: one uninterrupted run
    g = str(tmp_path / "golden")
    st_g, _, _ = train_chsac(duo_fleet, params(), out_dir=g,
                             ckpt_dir=str(tmp_path / "gc"),
                             obs=ObsConfig(out_dir=g, watchdog="off"), **kw)
    assert bool(st_g.done)
    golden = open(os.path.join(g, "metrics.jsonl"), "rb").read()
    assert golden, "golden run produced no metrics rows"

    # interrupted: stop after 2 chunks (checkpointed every chunk)
    r = str(tmp_path / "resumed")
    ck = str(tmp_path / "rc")
    train_chsac(duo_fleet, params(), out_dir=r, ckpt_dir=ck,
                max_chunks=2, obs=ObsConfig(out_dir=r, watchdog="off"),
                **kw)
    jsonl = os.path.join(r, "metrics.jsonl")
    partial = open(jsonl, "rb").read()
    assert 0 < len(partial) < len(golden), (
        "interrupt point must leave a proper prefix (got "
        f"{len(partial)} vs golden {len(golden)} bytes) — retune "
        "max_chunks/chunk_steps")
    assert golden.startswith(partial)
    # simulate a crash AFTER the last checkpoint: rows written past the
    # watermark must be dropped on resume, not duplicated
    with open(jsonl, "a") as f:
        f.write('{"t": 9e9, "crashed_past_checkpoint": true}\n')

    # resume: picks up at chunk 2, truncates to the watermark, appends
    st_r, _, _ = train_chsac(duo_fleet, params(), out_dir=r, ckpt_dir=ck,
                             obs=ObsConfig(out_dir=r, watchdog="off"),
                             **kw)
    assert bool(st_r.done)
    resumed = open(jsonl, "rb").read()
    assert b"crashed_past_checkpoint" not in resumed, (
        "rows past the checkpoint watermark survived the resume — they "
        "re-run and would appear twice")
    assert resumed == golden, (
        "resumed metrics.jsonl differs from the uninterrupted run "
        f"({len(resumed)} vs {len(golden)} bytes)")


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------

def test_chrome_trace_roundtrip(tmp_path):
    from distributed_cluster_gpus_tpu.obs.trace import PhaseTimer

    t = PhaseTimer(record_spans=True)
    with t.phase("rollout"):
        pass
    with t.phase("io"):
        pass
    t.add_span("io_render", 0.25)
    path = t.save_chrome_trace(str(tmp_path / "trace.json"))
    d = json.load(open(path))
    names = [e["name"] for e in d["traceEvents"]]
    assert names == ["rollout", "io", "io_render"]
    for e in d["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] >= 0 and e["ts"] >= 0
    # totals API unchanged (the summary the host loops print)
    assert t.counts["rollout"] == 1
    assert "io_render" in t.summary()


def test_merge_chrome_trace_unifies_host_and_device_lanes(tmp_path):
    """One Perfetto-loadable file: host phase spans + the jax.profiler
    device trace (round 14).  A fabricated profiler log dir stands in
    for the real trace (same gzip chrome-trace layout); a missing or
    corrupt device trace degrades to the host-only timeline with the
    reason recorded, never a raise."""
    import gzip

    from distributed_cluster_gpus_tpu.obs.trace import (
        PhaseTimer, merge_chrome_trace)

    t = PhaseTimer(record_spans=True)
    with t.phase("dispatch"):
        pass
    with t.phase("rollout"):
        pass

    prof = tmp_path / "prof" / "plugins" / "profile" / "run1"
    prof.mkdir(parents=True)
    dev_events = [
        # pid 0 metadata: profilers number processes from 0, so an
        # unshifted copy would relabel the HOST lane (review catch)
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "/device:TPU:0"}},
        {"name": "fusion.1", "ph": "X", "cat": "kernel",
         "ts": 1_000_000.5, "dur": 12.0, "pid": 0, "tid": 1},
        {"name": "fusion.2", "ph": "X", "cat": "kernel",
         "ts": 1_000_020.5, "dur": 7.0, "pid": 0, "tid": 1},
    ]
    with gzip.open(prof / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": dev_events}, f)

    path = merge_chrome_trace(t, str(tmp_path / "prof"),
                              str(tmp_path / "merged.json"))
    d = json.load(open(path))
    ev = d["traceEvents"]
    host = [e for e in ev if e.get("ph") == "X" and e.get("pid") == 0]
    dev = [e for e in ev if e.get("ph") == "X" and e.get("pid", 0) >= 1]
    assert [e["name"] for e in host] == ["dispatch", "rollout"]
    assert [e["name"] for e in dev] == ["fusion.1", "fusion.2"]
    # device lane re-zeroed at its own trace start (no shared clock)
    assert dev[0]["ts"] == 0.0 and dev[1]["ts"] == 20.0
    # process metadata labels both lanes, and the device's pid-0
    # process_name was SHIFTED with its events — exactly one name per
    # pid, the host lane keeps its own
    metas = [e for e in ev
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    procs = {}
    for e in metas:
        assert e["pid"] not in procs, f"duplicate name for pid {e['pid']}"
        procs[e["pid"]] = e["args"]["name"]
    assert procs[0] == "host phases (obs.trace.PhaseTimer)"
    assert procs[dev[0]["pid"]] == "/device:TPU:0"
    assert "alignment" in d["otherData"]

    # degradation: an empty profile dir yields host-only + a reason
    path2 = merge_chrome_trace(t, str(tmp_path / "nope"),
                               str(tmp_path / "host_only.json"))
    d2 = json.load(open(path2))
    assert [e["name"] for e in d2["traceEvents"]
            if e.get("ph") == "X"] == ["dispatch", "rollout"]
    assert "device_trace" in d2["otherData"]


def test_profiling_shim_removed():
    """The utils.profiling DeprecationWarning shim (PR 4) was deleted in
    round 10 — every in-tree call site imports obs.trace directly, and
    tier-1 output is warning-free again.  Pin the removal so the module
    does not quietly come back half-migrated."""
    import importlib.util

    assert importlib.util.find_spec(
        "distributed_cluster_gpus_tpu.utils.profiling") is None, (
        "utils.profiling is back — the shim was removed in round 10; "
        "import PhaseTimer/sim_progress/trace from obs.trace")
    from distributed_cluster_gpus_tpu.obs.trace import (  # noqa: F401
        PhaseTimer, sim_progress, trace)


# ---------------------------------------------------------------------------
# watchdog 'raise' abort path (PR 8 satellite): flush before aborting
# ---------------------------------------------------------------------------

def test_watchdog_raise_flushes_exporters_before_abort(duo_fleet, tmp_path):
    """Regression: a watchdog abort must FLUSH the drains and write the
    aborted run_summary.json instead of stranding buffered rows.

    Forced-NaN integration path: a corrupted initial state (NaN energy)
    trips the nonfinite-energy probe in the very first chunk; the
    pipelined run_simulation loop under mode='raise' must still land
    the chunk's CSV/JSONL rows on disk and stamp status='aborted'
    before the WatchdogError unwinds."""
    params = SimParams(obs_enabled=True, **DUO_KW)
    eng = Engine(duo_fleet, params)
    st0 = init_state(jax.random.key(0), duo_fleet, params,
                     workload=eng.workload)
    st0 = st0.replace(dc=st0.dc.replace(
        energy_j=st0.dc.energy_j.at[0].set(jnp.nan)))
    d = str(tmp_path / "abort")
    with pytest.raises(WatchdogError):
        run_simulation(duo_fleet, params, out_dir=d, chunk_steps=256,
                       obs=ObsConfig(out_dir=d, watchdog="raise"),
                       state0=st0)
    # the tripping chunk's stream is on disk, not stranded in a queue
    assert os.path.getsize(os.path.join(d, "cluster_log.csv")) > 64
    recs = [json.loads(line)
            for line in open(os.path.join(d, "metrics.jsonl"))]
    assert recs, "metrics.jsonl stranded by the abort"
    rs = json.load(open(os.path.join(d, "run_summary.json")))
    assert rs["status"] == "aborted"
    assert rs["watchdog"]["mode"] == "raise"
    assert rs["watchdog"]["violations"]["nonfinite_energy"] > 0

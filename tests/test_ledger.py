"""Perf ledger contracts: deterministic rebuild, idempotent ingest,
corrupt-round degradation, and the --check regression gate's exit codes.

The ledger is the round-trip memory of every banked perf number, so the
properties under test are exactly the ones a future round relies on:
rebuilding from the same banked files is byte-identical, re-ingesting
adds nothing, a corrupt artifact becomes one logged reason (never a
traceback), platform classes never cross-compare, and an injected ev/s
regression flips the CLI to a nonzero exit while the repo's real banked
trajectory passes.
"""

import importlib.util
import json
import os

import pytest

from distributed_cluster_gpus_tpu.analysis import ledger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli():
    spec = importlib.util.spec_from_file_location(
        "perf_ledger", os.path.join(REPO, "scripts", "perf_ledger.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_wrapper(n, value, platform="cpu", rows=None):
    parsed = {"metric": "sim_job_steps_per_sec_rl_in_loop",
              "value": value, "unit": "events/sec",
              "platform": platform,
              "config": {"rollouts": 32, "job_cap": 128}}
    if rows:
        parsed["configs_measured"] = rows
    return {"n": n, "cmd": "python bench.py", "rc": 0, "tail": "",
            "parsed": parsed}


@pytest.fixture()
def banked(tmp_path):
    """A miniature banked-evidence tree mirroring the real layout."""
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "bench_results"))

    def w(rel, payload):
        with open(os.path.join(root, rel), "w") as f:
            if isinstance(payload, str):
                f.write(payload)
            else:
                json.dump(payload, f)

    w("BENCH_r01.json", {"n": 1, "rc": 1, "tail": "boom", "parsed": None})
    w("BENCH_r02.json", _bench_wrapper(2, 20500.0))
    w("BENCH_r03.json", _bench_wrapper(3, 22100.0))
    w("MULTICHIP_r03.json", {"n_devices": 8, "rc": 0, "ok": True,
                             "skipped": False, "tail": "ok"})
    w(os.path.join("bench_results", "superstep_r06.json"), {
        "platform": "cpu",
        "superstep_sweep": {"algo": "joint_nf",
                            "shape": {"rollouts": 32, "job_cap": 128},
                            "rows": [
                                {"superstep_k": 1, "events_per_sec": 12000.0,
                                 "events_per_iteration": 1.0,
                                 "step_body_eqns": 1841},
                                {"superstep_k": 4, "events_per_sec": 14000.0,
                                 "events_per_iteration": 2.9,
                                 "step_body_eqns": 2741},
                            ]}})
    w(os.path.join("bench_results", "corrupt_r04.json"), "{not json")
    w(os.path.join("bench_results", "debris_r04.json.tmp"), "{}")
    w(os.path.join("bench_results", "key_r05.json"), {
        "platform": "tpu", "value": 88000.0,
        "config": {"rollouts": 256, "job_cap": 128}})
    return root


def test_discovery_one_rule_excludes_debris(banked):
    rels = ledger.discover(banked)
    assert "BENCH_r02.json" in rels and "MULTICHIP_r03.json" in rels
    assert os.path.join("bench_results", "superstep_r06.json") in rels
    assert not any(r.endswith(".tmp") for r in rels)
    # the ledger itself must never be re-ingested as evidence
    ledger.rebuild(banked)
    assert os.path.join("bench_results",
                        "ledger.jsonl") not in ledger.discover(banked)


def test_rebuild_byte_identical(banked):
    path = ledger.ledger_path(banked)
    ledger.rebuild(banked, path)
    first = open(path, "rb").read()
    assert first, "empty ledger from non-empty banked tree"
    ledger.rebuild(banked, path)
    assert open(path, "rb").read() == first


def test_rebuild_from_real_banked_rounds_byte_identical(tmp_path):
    """The acceptance gate on the repo's OWN artifacts: two rebuilds of
    the real banked set are byte-identical."""
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    ledger.rebuild(REPO, p1)
    ledger.rebuild(REPO, p2)
    b1 = open(p1, "rb").read()
    assert b1 and b1 == open(p2, "rb").read()
    for line in b1.splitlines():
        assert json.loads(line)["schema"] == "dcg.perf_ledger.v1"


def test_ingest_idempotent(banked):
    first = ledger.ingest(banked)
    assert first["added"] > 0
    again = ledger.ingest(banked)
    assert again["added"] == 0
    assert again["total"] == first["total"]


def test_ingest_appends_only_new_rounds(banked):
    ledger.ingest(banked)
    with open(os.path.join(banked, "BENCH_r04.json"), "w") as f:
        json.dump(_bench_wrapper(4, 21000.0), f)
    res = ledger.ingest(banked)
    assert res["added"] == 1
    recs = ledger.read_ledger(ledger.ledger_path(banked))
    assert any(r["source"] == "BENCH_r04.json" for r in recs)


def test_corrupt_and_unparsed_rounds_degrade_to_reasons(banked):
    records, skipped = ledger.build_records(banked)
    reasons = dict(skipped)
    assert "BENCH_r01.json" in reasons  # wrapper without a parsed line
    assert os.path.join("bench_results", "corrupt_r04.json") in reasons
    assert all(isinstance(why, str) and why for why in reasons.values())
    # the corrupt file contributed no records; the good ones all did
    assert not any(r["source"].endswith("corrupt_r04.json")
                   for r in records)


def test_records_normalize_kinds_and_fill(banked):
    records, _ = ledger.build_records(banked)
    kinds = {r["kind"] for r in records}
    assert {"headline", "superstep", "multichip"} <= kinds
    k4 = next(r for r in records if r["kind"] == "superstep"
              and r["config"] == "joint_nf/K4")
    assert k4["fill"] == pytest.approx(2.9 / 4, abs=1e-4)
    assert k4["round"] == 6
    chip = next(r for r in records if r["source"].endswith("key_r05.json"))
    assert ledger.platform_class(chip["platform"]) == "chip"


def test_check_passes_real_trajectory_and_flags_injected_regression(
        banked):
    ledger.rebuild(banked)
    records = ledger.read_ledger(ledger.ledger_path(banked))
    # the banked trajectory itself: r03 (22100) vs best 22100 — clean
    ok_doc = _bench_wrapper(3, 22100.0)["parsed"]
    assert ledger.check(records,
                        ledger.records_from("BENCH_r03.json", ok_doc)) == []
    # a mild dip inside the threshold passes too
    dip = _bench_wrapper(6, 20000.0)["parsed"]
    assert ledger.check(records,
                        ledger.records_from("BENCH_r06.json", dip)) == []
    # an injected collapse beyond the threshold is flagged
    bad = _bench_wrapper(6, 5000.0)["parsed"]
    flags = ledger.check(records,
                         ledger.records_from("BENCH_r06.json", bad))
    assert len(flags) == 1
    assert flags[0]["drop_fraction"] > 0.3
    assert flags[0]["platform_class"] == "cpu"


def test_check_never_crosses_platform_classes(banked):
    ledger.rebuild(banked)
    records = ledger.read_ledger(ledger.ledger_path(banked))
    # a CPU probe far below the banked on-chip best (88k) but on the
    # real CPU trajectory must pass: cpu never gates against chip
    doc = _bench_wrapper(6, 21000.0)["parsed"]
    assert ledger.check(records,
                        ledger.records_from("BENCH_r06.json", doc)) == []


def test_cli_exit_codes_and_one_line_degradation(banked, tmp_path,
                                                 capsys):
    cli = _cli()
    ok = cli.main(["--root", banked, "--rebuild", "--trend"])
    out = capsys.readouterr().out
    assert ok == 0
    assert out.count("BENCH_r01.json") == 1  # ONE summary line, no spam
    assert "### headline ev/s by round" in out

    # real trajectory: exit 0
    assert cli.main(["--root", banked, "--check",
                     os.path.join(banked, "BENCH_r03.json")]) == 0
    # injected regression: nonzero exit + report says so
    bad = tmp_path / "BENCH_regressed.json"
    bad.write_text(json.dumps(_bench_wrapper(9, 4000.0)))
    rep_path = tmp_path / "rep.json"
    rc = cli.main(["--root", banked, "--check", str(bad),
                   "--json", str(rep_path)])
    assert rc == 1
    rep = json.loads(rep_path.read_text())
    assert rep["schema"] == "dcg.lint_report.v1"
    assert not rep["ok"]
    assert any(v["rule"] == "ledger-regression"
               for v in rep["violations"])
    # unreadable --check input is an error exit, not a traceback
    missing = tmp_path / "nope.json"
    assert cli.main(["--root", banked, "--check", str(missing)]) == 1


def test_real_repo_trajectory_holds(tmp_path):
    """The repo's own banked rounds: the newest headline bench must hold
    the ledger's trajectory at the default threshold (this IS the gate
    bench.py banks per round)."""
    path = str(tmp_path / "ledger.jsonl")
    ledger.rebuild(REPO, path)
    records = ledger.read_ledger(path)
    doc, reason = ledger.load_banked(REPO, "BENCH_r05.json")
    assert reason is None, reason
    assert ledger.check(records,
                        ledger.records_from("BENCH_r05.json", doc)) == []


def test_bench_prior_evidence_shares_loader(banked):
    import sys

    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    best = bench.best_prior_on_chip(root=banked)
    assert best["events_per_sec"] == 88000.0
    assert best["rollouts"] == 256 and best["job_cap"] == 128
    assert best["file"] == os.path.join("bench_results", "key_r05.json")

"""Native CSV writer: byte-identity with the Python path + drain throughput.

The host-side CSV drain is the one serial component of long runs (the
reference's inline csv.writer, `simulator_paper_multi.py:814-823, 929-948`).
`native/csv_writer.cpp` renders the same printf formats at fwrite speed;
these tests prove the outputs are byte-identical and that the native path is
actually faster on a >=100k-row drain (otherwise it has no reason to exist).
"""

import time

import numpy as np
import pytest

from distributed_cluster_gpus_tpu.sim.io import CSVWriters
from distributed_cluster_gpus_tpu.utils.native import csv_writer_lib

pytestmark = pytest.mark.skipif(csv_writer_lib() is None,
                                reason="native csv writer did not build")


def _cluster_rows(rng, n_ticks, n_dc):
    rows = rng.random((n_ticks, n_dc, 14)).astype(np.float32)
    rows[..., 0] = np.cumsum(rng.random(n_ticks)[:, None] * 20.0, axis=0)  # time_s
    for col in (2, 3, 4, 5, 6, 7, 8):  # integer-rendered columns
        rows[..., col] = rng.integers(0, 512, (n_ticks, n_dc))
    rows[..., 12] *= 1e5  # power_W scale
    return rows


def _job_rows(rng, n, n_ing, n_dc):
    rows = rng.random((n, 15)).astype(np.float32)
    rows[:, 0] = np.arange(n)  # jid
    rows[:, 1] = rng.integers(0, n_ing, n)
    rows[:, 2] = rng.integers(0, 2, n)
    rows[:, 4] = rng.integers(0, n_dc, n)
    rows[:, 6] = rng.integers(1, 9, n)
    rows[:, 11] = rng.integers(0, 3, n)
    rows[:, 8] *= 6e5  # start_s at long-horizon magnitudes
    rows[:, 9] = rows[:, 8] + rows[:, 10]
    return rows


def test_cluster_byte_identity(tmp_path, fleet, rng):
    rows = _cluster_rows(rng, 50, fleet.n_dc)
    idxs = list(range(50))
    wn = CSVWriters(str(tmp_path / "nat"), fleet, use_native=True)
    assert wn._lib is not None
    wp = CSVWriters(str(tmp_path / "py"), fleet, use_native=False)
    wn.write_cluster_chunk(rows, idxs)
    wp.write_cluster_chunk(rows, idxs)
    nat = (tmp_path / "nat" / "cluster_log.csv").read_bytes()
    py = (tmp_path / "py" / "cluster_log.csv").read_bytes()
    assert nat == py


def test_job_byte_identity(tmp_path, fleet, rng):
    rows = _job_rows(rng, 200, fleet.n_ing, fleet.n_dc)
    idxs = list(range(200))
    wn = CSVWriters(str(tmp_path / "nat"), fleet, use_native=True)
    wp = CSVWriters(str(tmp_path / "py"), fleet, use_native=False)
    wn.write_job_chunk(rows, idxs)
    wp.write_job_chunk(rows, idxs)
    assert ((tmp_path / "nat" / "job_log.csv").read_bytes()
            == (tmp_path / "py" / "job_log.csv").read_bytes())


def test_native_faster_on_big_drain(tmp_path, fleet, rng):
    n = 100_000
    rows = _job_rows(rng, n, fleet.n_ing, fleet.n_dc)
    idxs = np.arange(n)
    wn = CSVWriters(str(tmp_path / "nat"), fleet, use_native=True)
    wp = CSVWriters(str(tmp_path / "py"), fleet, use_native=False)

    t0 = time.perf_counter()
    wn.write_job_chunk(rows, idxs)
    t_nat = time.perf_counter() - t0
    t0 = time.perf_counter()
    wp.write_job_chunk(rows, idxs)
    t_py = time.perf_counter() - t0

    assert ((tmp_path / "nat" / "job_log.csv").read_bytes()
            == (tmp_path / "py" / "job_log.csv").read_bytes())
    assert t_nat < t_py, f"native {t_nat:.3f}s not faster than python {t_py:.3f}s"

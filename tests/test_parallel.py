"""Scale-out tests on the virtual 8-device CPU mesh.

This is the JAX idiom for testing multi-chip behavior without hardware
(SURVEY.md §4e): the same `shard_map` program the TPU runs, executed over
`--xla_force_host_platform_device_count=8` CPU devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.parallel import DistributedTrainer, batched_init, make_mesh


@pytest.fixture(scope="module")
def chsac_params():
    return SimParams(algo="chsac_af", duration=60.0, log_interval=5.0,
                     inf_mode="poisson", inf_rate=4.0,
                     trn_mode="poisson", trn_rate=0.1,
                     rl_warmup=32, rl_batch=32, job_cap=64, lat_window=128,
                     seed=5)


def test_mesh_uses_all_devices():
    mesh = make_mesh()
    assert mesh.devices.size == len(jax.devices()) == 8


def test_batched_init_independent_streams(single_dc_fleet, chsac_params):
    states = batched_init(single_dc_fleet, chsac_params, 4)
    # each rollout has a distinct PRNG stream -> distinct first arrivals
    arr = np.asarray(states.next_arrival).reshape(4, -1)
    assert len({tuple(r) for r in arr.tolist()}) == 4


class TestDistributedTrainer:
    @pytest.fixture(scope="class")
    def trainer(self, fleet, chsac_params):
        tr = DistributedTrainer(fleet, chsac_params, n_rollouts=16,
                                mesh=make_mesh(),
                                replay_capacity_per_shard=4096,
                                sac_steps_per_chunk=2)
        tr.metrics = tr.train_chunk(chunk_steps=48)
        return tr

    def test_progresses_and_learns(self, trainer):
        m = trainer.metrics
        assert int(m["n_events"]) == 16 * 48
        assert np.isfinite(float(m["critic_loss"]))
        assert int(m["n_finished"]) > 0
        # warmup gate: updates only run once EVERY shard's replay holds
        # rl_warmup transitions (mesh-agreed pmin predicate)
        if not bool(m["warmed"]):
            assert int(trainer.sac.step) == 0

    def test_sac_replicated_states_sharded(self, trainer):
        from jax.sharding import PartitionSpec as P

        leaf = jax.tree.leaves(trainer.sac.actor_params)[0]
        assert leaf.sharding.spec == P()
        assert trainer.states.t.sharding.spec == P("rollout")
        assert jax.tree.leaves(trainer.replay.s0)[0].sharding.spec == P("rollout")

    def test_second_chunk_advances_time(self, trainer):
        t_before = np.asarray(trainer.states.t).copy()
        m = trainer.train_chunk(chunk_steps=48)
        t_after = np.asarray(trainer.states.t)
        assert (t_after >= t_before).all()
        assert (t_after > t_before).any()
        # by now all shards are warmed: this chunk's 2 SAC steps ran (the
        # first chunk's were warmup-gated away unless it already warmed)
        assert bool(m["warmed"])
        expected = 2 * (2 if bool(trainer.metrics["warmed"]) else 1)
        assert int(trainer.sac.step) == expected


def test_gradient_allreduce_matches_single_device(fleet):
    """pmean-synced SAC params must stay bit-identical across shards."""
    params = SimParams(algo="chsac_af", duration=30.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=3.0, trn_mode="off",
                       rl_warmup=8, rl_batch=16, job_cap=32, lat_window=64,
                       seed=9)
    tr = DistributedTrainer(fleet, params, n_rollouts=8, mesh=make_mesh(),
                            replay_capacity_per_shard=512)
    tr.train_chunk(chunk_steps=32)
    # fetch the replicated actor params from two different devices; identical
    leaf = jax.tree.leaves(tr.sac.actor_params)[0]
    shards = leaf.addressable_shards
    a = np.asarray(shards[0].data)
    b = np.asarray(shards[-1].data)
    np.testing.assert_array_equal(a, b)


class TestDCNMesh:
    """The 2-axis (dcn, rollout) mesh: the multi-host scale-out program
    validated on the virtual CPU mesh (SURVEY.md §5 distributed backend).
    On one host the dcn hops are just more ICI — the point is that the
    hierarchical-collective program compiles, runs, and computes the same
    global means as the flat 1-axis mesh."""

    def test_mesh_shape_and_axes(self):
        mesh = make_mesh(dcn=2)
        assert mesh.axis_names == ("dcn", "rollout")
        assert mesh.devices.shape == (2, 4)
        with pytest.raises(ValueError, match="split"):
            make_mesh(dcn=3)

    def test_trainer_on_dcn_mesh_matches_flat_mesh(self, fleet, chsac_params):
        """Same seeds, same rollouts: gradient pmean over ("dcn","rollout")
        must give the same learning trajectory as over a flat 8-device
        mesh (a global mean either way), and the rollout batch must
        actually shard over both axes."""
        from jax.sharding import PartitionSpec as P

        kw = dict(n_rollouts=16, replay_capacity_per_shard=2048,
                  sac_steps_per_chunk=1, seed=3)
        tr2 = DistributedTrainer(fleet, chsac_params,
                                 mesh=make_mesh(dcn=2), **kw)
        tr1 = DistributedTrainer(fleet, chsac_params,
                                 mesh=make_mesh(), **kw)
        for _ in range(3):  # enough chunks that every shard must warm up
            m2 = tr2.train_chunk(chunk_steps=64)
            m1 = tr1.train_chunk(chunk_steps=64)
        assert tr2.states.t.sharding.spec == P(("dcn", "rollout"))
        assert int(m2["n_events"]) == int(m1["n_events"]) == 3 * 16 * 64
        # identical sim trajectories; losses equal to reduction tolerance.
        # warmed must be reached or the loss comparison proves nothing
        np.testing.assert_allclose(np.asarray(tr2.states.t),
                                   np.asarray(tr1.states.t), rtol=1e-6)
        assert bool(m1["warmed"]) and bool(m2["warmed"])
        np.testing.assert_allclose(float(m2["critic_loss"]),
                                   float(m1["critic_loss"]), rtol=1e-4)
        # replicated learner params stay identical across ALL 8 devices
        leaf = jax.tree.leaves(tr2.sac.actor_params)[0]
        shards = leaf.addressable_shards
        for s in shards[1:]:
            np.testing.assert_array_equal(np.asarray(shards[0].data),
                                          np.asarray(s.data))

    def test_ppo_on_dcn_mesh(self, fleet):
        from distributed_cluster_gpus_tpu.parallel.rollout import PPOTrainer

        params = SimParams(algo="chsac_af", duration=30.0, log_interval=5.0,
                           inf_mode="poisson", inf_rate=3.0, trn_mode="off",
                           job_cap=32, lat_window=64, seed=9)
        tr = PPOTrainer(fleet, params, n_rollouts=8, mesh=make_mesh(dcn=4))
        m = tr.train_chunk(chunk_steps=32)
        assert int(m["n_events"]) == 8 * 32
        assert np.isfinite(float(m["pg_loss"]))


def test_rollout_bit_parity_across_mesh_sizes(fleet, chsac_params):
    """A rollout's trajectory must not depend on how many devices the
    batch is sharded over (VERDICT r04 item 7a): the same 8-lane vmapped
    engine chunk, run on one device vs shard_mapped over the 8-device
    mesh, yields bit-identical SimStates for every lane.

    Uses the deterministic-policy-stub helper shared with the driver's
    dryrun (`parallel.engine_shard_parity`): the real actor's bf16
    matmuls legitimately change reduction order with the per-device batch
    shape (B=8 on one device vs B=1 per device on eight), which can flip
    a *sampled* action — measured: 1 slab element in 512 diverged — so
    bitwise parity is a property of the sharded ENGINE program, asserted
    here, not of trajectories that route through the network (those are
    compared at tolerance by the DCN-mesh trainer test)."""
    from distributed_cluster_gpus_tpu.parallel import engine_shard_parity

    engine_shard_parity(fleet, chsac_params, make_mesh(8), n_rollouts=8,
                        chunk_steps=64)


def test_aggregate_throughput_scales_with_devices(fleet, chsac_params):
    """Scaling shape (VERDICT r04 item 7b): with a fixed per-device rollout
    count, the sharded program's aggregate events per chunk scales linearly
    with device count.  The EVENT-COUNT scaling is the assertion; the
    wall-clock throughput ratio is only reported — all 8 virtual devices
    share one physical core, so the timing ratio measures CI contention
    and compile-cache luck, not the program (it flaked as an assert)."""
    import dataclasses
    import time

    params = dataclasses.replace(chsac_params, rl_warmup=1_000_000)
    rates = {}
    for n in (1, 8):
        tr = DistributedTrainer(fleet, params, n_rollouts=2 * n,
                                mesh=make_mesh(n),
                                replay_capacity_per_shard=1024)
        m = tr.train_chunk(chunk_steps=32)  # compile + warmup
        ev0 = int(m["n_events"])  # n_events accumulates across chunks
        t0 = time.perf_counter()
        m = tr.train_chunk(chunk_steps=32)
        jax.block_until_ready(tr.states.t)
        wall = time.perf_counter() - t0
        events = int(m["n_events"]) - ev0
        assert events == 2 * n * 32  # aggregate events scale with devices
        rates[n] = events / wall
    print(f"virtual-mesh throughput ratio 8dev/1dev: "
          f"{rates[8] / rates[1]:.2f}x (informational only)")

"""Scale-out tests on the virtual 8-device CPU mesh.

This is the JAX idiom for testing multi-chip behavior without hardware
(SURVEY.md §4e): the same `shard_map` program the TPU runs, executed over
`--xla_force_host_platform_device_count=8` CPU devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.parallel import DistributedTrainer, batched_init, make_mesh


@pytest.fixture(scope="module")
def chsac_params():
    return SimParams(algo="chsac_af", duration=60.0, log_interval=5.0,
                     inf_mode="poisson", inf_rate=4.0,
                     trn_mode="poisson", trn_rate=0.1,
                     rl_warmup=32, rl_batch=32, job_cap=64, lat_window=128,
                     seed=5)


def test_mesh_uses_all_devices():
    mesh = make_mesh()
    assert mesh.devices.size == len(jax.devices()) == 8


def test_batched_init_independent_streams(single_dc_fleet, chsac_params):
    states = batched_init(single_dc_fleet, chsac_params, 4)
    # each rollout has a distinct PRNG stream -> distinct first arrivals
    arr = np.asarray(states.next_arrival).reshape(4, -1)
    assert len({tuple(r) for r in arr.tolist()}) == 4


class TestDistributedTrainer:
    @pytest.fixture(scope="class")
    def trainer(self, fleet, chsac_params):
        tr = DistributedTrainer(fleet, chsac_params, n_rollouts=16,
                                mesh=make_mesh(),
                                replay_capacity_per_shard=4096,
                                sac_steps_per_chunk=2)
        tr.metrics = tr.train_chunk(chunk_steps=48)
        return tr

    def test_progresses_and_learns(self, trainer):
        m = trainer.metrics
        assert int(m["n_events"]) == 16 * 48
        assert np.isfinite(float(m["critic_loss"]))
        assert int(m["n_finished"]) > 0
        # warmup gate: updates only run once EVERY shard's replay holds
        # rl_warmup transitions (mesh-agreed pmin predicate)
        if not bool(m["warmed"]):
            assert int(trainer.sac.step) == 0

    def test_sac_replicated_states_sharded(self, trainer):
        from jax.sharding import PartitionSpec as P

        leaf = jax.tree.leaves(trainer.sac.actor_params)[0]
        assert leaf.sharding.spec == P()
        assert trainer.states.t.sharding.spec == P("rollout")
        assert jax.tree.leaves(trainer.replay.s0)[0].sharding.spec == P("rollout")

    def test_second_chunk_advances_time(self, trainer):
        t_before = np.asarray(trainer.states.t).copy()
        m = trainer.train_chunk(chunk_steps=48)
        t_after = np.asarray(trainer.states.t)
        assert (t_after >= t_before).all()
        assert (t_after > t_before).any()
        # by now all shards are warmed: this chunk's 2 SAC steps ran (the
        # first chunk's were warmup-gated away unless it already warmed)
        assert bool(m["warmed"])
        expected = 2 * (2 if bool(trainer.metrics["warmed"]) else 1)
        assert int(trainer.sac.step) == expected


def test_gradient_allreduce_matches_single_device(fleet):
    """pmean-synced SAC params must stay bit-identical across shards."""
    params = SimParams(algo="chsac_af", duration=30.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=3.0, trn_mode="off",
                       rl_warmup=8, rl_batch=16, job_cap=32, lat_window=64,
                       seed=9)
    tr = DistributedTrainer(fleet, params, n_rollouts=8, mesh=make_mesh(),
                            replay_capacity_per_shard=512)
    tr.train_chunk(chunk_steps=32)
    # fetch the replicated actor params from two different devices; identical
    leaf = jax.tree.leaves(tr.sac.actor_params)[0]
    shards = leaf.addressable_shards
    a = np.asarray(shards[0].data)
    b = np.asarray(shards[-1].data)
    np.testing.assert_array_equal(a, b)

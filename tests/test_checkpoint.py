"""Checkpoint/resume: full pipeline state round-trips bit-exactly.

Capability the reference lacks entirely (SURVEY.md §5): the RL agent, replay
buffer, and simulator state all persist and resume mid-run.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.rl.cmdp import N_COSTS, default_constraints
from distributed_cluster_gpus_tpu.rl.replay import replay_add_chunk, replay_init
from distributed_cluster_gpus_tpu.rl.sac import SACConfig, sac_init, sac_train_step
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
from distributed_cluster_gpus_tpu.utils.checkpoint import (
    latest_step, restore_checkpoint, save_checkpoint,
)


def test_roundtrip_sac_and_sim(tmp_path, single_dc_fleet):
    cfg = SACConfig(obs_dim=13, n_dc=2, n_g=4, batch=8, n_quantiles=8,
                    latent=32, constraints=default_constraints())
    sac = sac_init(cfg, jax.random.key(0))
    rb = replay_init(64, 13, 2, 4, N_COSTS)
    tr = {
        "valid": jnp.ones((16,), bool),
        "s0": jnp.arange(16 * 13, dtype=jnp.float32).reshape(16, 13),
        "s1": jnp.zeros((16, 13)), "a_dc": jnp.zeros((16,), jnp.int32),
        "a_g": jnp.zeros((16,), jnp.int32), "r": jnp.ones((16,)),
        "costs": jnp.zeros((16, N_COSTS)),
        "mask_dc": jnp.ones((16, 2), bool), "mask_g": jnp.ones((16, 4), bool),
    }
    rb = replay_add_chunk(rb, tr)
    sac, _ = sac_train_step(cfg, sac, rb, jax.random.key(1))

    params = SimParams(algo="default_policy", duration=30.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=2.0, trn_mode="off",
                       job_cap=64, seed=2)
    engine = Engine(single_dc_fleet, params)
    state = init_state(jax.random.key(2), single_dc_fleet, params)
    state, _ = engine.run_chunk(state, None, n_steps=128)

    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(ckpt, step=7, sac=sac, replay=rb, sim=state)
    assert latest_step(ckpt) == 7

    def leaves_np(tree):
        def conv(x):
            if isinstance(x, jax.Array) and jax.dtypes.issubdtype(
                    x.dtype, jax.dtypes.prng_key):
                return np.asarray(jax.random.key_data(x))
            return np.asarray(x)
        return [conv(x) for x in jax.tree.leaves(tree)]

    out = restore_checkpoint(ckpt, like={"sac": sac, "replay": rb, "sim": state})
    for name, orig in (("sac", sac), ("replay", rb), ("sim", state)):
        for a, b in zip(leaves_np(orig), leaves_np(out[name])):
            np.testing.assert_array_equal(a, b)


def test_resume_continues_identically(tmp_path, single_dc_fleet):
    """A restored sim state must continue exactly like the original."""
    params = SimParams(algo="joint_nf", duration=60.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=2.0, trn_mode="off",
                       job_cap=64, seed=4)
    engine = Engine(single_dc_fleet, params)
    state = init_state(jax.random.key(4), single_dc_fleet, params)
    state, _ = engine.run_chunk(state, None, n_steps=64)

    ckpt = str(tmp_path / "c2")
    save_checkpoint(ckpt, step=0, sim=state)
    restored = restore_checkpoint(ckpt, like={"sim": state})["sim"]

    cont_a, _ = engine.run_chunk(state, None, n_steps=64)
    cont_b, _ = engine.run_chunk(restored, None, n_steps=64)
    np.testing.assert_array_equal(np.asarray(cont_a.t), np.asarray(cont_b.t))
    np.testing.assert_array_equal(np.asarray(cont_a.jobs.status),
                                  np.asarray(cont_b.jobs.status))
    np.testing.assert_array_equal(np.asarray(cont_a.dc.energy_j),
                                  np.asarray(cont_b.dc.energy_j))


def test_warm_sac_from_checkpoint_grafts_policy_only(tmp_path):
    """Policy warm-start across critic architectures: the donor's encoder
    and actor transfer; critic/targets/alpha/step stay fresh — the graft
    must work when the donor used a DIFFERENT critic arch (the canonical
    week used 'heads', the hour-scale eval 'onehot')."""
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import SACConfig, sac_init
    from distributed_cluster_gpus_tpu.rl.train import warm_sac_from_checkpoint
    from distributed_cluster_gpus_tpu.utils.checkpoint import save_checkpoint

    donor_cfg = SACConfig(obs_dim=13, n_dc=2, n_g=4, critic_arch="heads",
                          constraints=default_constraints())
    donor = sac_init(donor_cfg, jax.random.key(7))
    ckpt = str(tmp_path / "wk")
    save_checkpoint(ckpt, step=3, sac=donor)

    tgt_cfg = SACConfig(obs_dim=13, n_dc=2, n_g=4, critic_arch="onehot",
                        constraints=default_constraints())
    warm = warm_sac_from_checkpoint(tgt_cfg, ckpt, jax.random.key(8))
    fresh = sac_init(tgt_cfg, jax.random.key(8))

    for grafted, donor_p in ((warm.actor_params, donor.actor_params),
                             (warm.enc_params, donor.enc_params)):
        for a, b in zip(jax.tree.leaves(grafted), jax.tree.leaves(donor_p)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # critic arch differs from the donor's -> must be the fresh init
    for a, b in zip(jax.tree.leaves(warm.critic_params),
                    jax.tree.leaves(fresh.critic_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(warm.log_alpha) == float(fresh.log_alpha)
    assert int(warm.step) == 0

"""Checkpoint/resume: full pipeline state round-trips bit-exactly, and
(round 12) the store is crash-consistent and verified.

Capability the reference lacks entirely (SURVEY.md §5): the RL agent, replay
buffer, and simulator state all persist and resume mid-run.  The verified-
store suite below proves the atomic-commit contract with a crash-injection
harness (every env-gated fault point + a real SIGKILL mid-save subprocess):
after a crash at any point the store contains only checkpoints
verify_checkpoint accepts, gc sweeps the staging debris, and resume
restores the newest verified step.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.rl.cmdp import N_COSTS, default_constraints
from distributed_cluster_gpus_tpu.rl.replay import replay_add_chunk, replay_init
from distributed_cluster_gpus_tpu.rl.sac import SACConfig, sac_init, sac_train_step
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
from distributed_cluster_gpus_tpu.utils.checkpoint import (
    CRASH_POINTS, CheckpointCorruptError, CheckpointCrashInjected,
    gc_checkpoints, latest_step, restore_checkpoint, restore_latest,
    save_checkpoint, step_dirname, steps, verify_checkpoint,
)

HERE = os.path.dirname(os.path.abspath(__file__))


def test_roundtrip_sac_and_sim(tmp_path, single_dc_fleet):
    cfg = SACConfig(obs_dim=13, n_dc=2, n_g=4, batch=8, n_quantiles=8,
                    latent=32, constraints=default_constraints())
    sac = sac_init(cfg, jax.random.key(0))
    rb = replay_init(64, 13, 2, 4, N_COSTS)
    tr = {
        "valid": jnp.ones((16,), bool),
        "s0": jnp.arange(16 * 13, dtype=jnp.float32).reshape(16, 13),
        "s1": jnp.zeros((16, 13)), "a_dc": jnp.zeros((16,), jnp.int32),
        "a_g": jnp.zeros((16,), jnp.int32), "r": jnp.ones((16,)),
        "costs": jnp.zeros((16, N_COSTS)),
        "mask_dc": jnp.ones((16, 2), bool), "mask_g": jnp.ones((16, 4), bool),
    }
    rb = replay_add_chunk(rb, tr)
    sac, _ = sac_train_step(cfg, sac, rb, jax.random.key(1))

    params = SimParams(algo="default_policy", duration=30.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=2.0, trn_mode="off",
                       job_cap=64, seed=2)
    engine = Engine(single_dc_fleet, params)
    state = init_state(jax.random.key(2), single_dc_fleet, params)
    state, _ = engine.run_chunk(state, None, n_steps=128)

    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(ckpt, step=7, sac=sac, replay=rb, sim=state)
    assert latest_step(ckpt) == 7

    def leaves_np(tree):
        def conv(x):
            if isinstance(x, jax.Array) and jax.dtypes.issubdtype(
                    x.dtype, jax.dtypes.prng_key):
                return np.asarray(jax.random.key_data(x))
            return np.asarray(x)
        return [conv(x) for x in jax.tree.leaves(tree)]

    out = restore_checkpoint(ckpt, like={"sac": sac, "replay": rb, "sim": state})
    for name, orig in (("sac", sac), ("replay", rb), ("sim", state)):
        for a, b in zip(leaves_np(orig), leaves_np(out[name])):
            np.testing.assert_array_equal(a, b)


def test_resume_continues_identically(tmp_path, single_dc_fleet):
    """A restored sim state must continue exactly like the original."""
    params = SimParams(algo="joint_nf", duration=60.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=2.0, trn_mode="off",
                       job_cap=64, seed=4)
    engine = Engine(single_dc_fleet, params)
    state = init_state(jax.random.key(4), single_dc_fleet, params)
    state, _ = engine.run_chunk(state, None, n_steps=64)

    ckpt = str(tmp_path / "c2")
    save_checkpoint(ckpt, step=0, sim=state)
    restored = restore_checkpoint(ckpt, like={"sim": state})["sim"]

    cont_a, _ = engine.run_chunk(state, None, n_steps=64)
    cont_b, _ = engine.run_chunk(restored, None, n_steps=64)
    np.testing.assert_array_equal(np.asarray(cont_a.t), np.asarray(cont_b.t))
    np.testing.assert_array_equal(np.asarray(cont_a.jobs.status),
                                  np.asarray(cont_b.jobs.status))
    np.testing.assert_array_equal(np.asarray(cont_a.dc.energy_j),
                                  np.asarray(cont_b.dc.energy_j))


def test_warm_sac_from_checkpoint_grafts_policy_only(tmp_path):
    """Policy warm-start across critic architectures: the donor's encoder
    and actor transfer; critic/targets/alpha/step stay fresh — the graft
    must work when the donor used a DIFFERENT critic arch (the canonical
    week used 'heads', the hour-scale eval 'onehot')."""
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.sac import SACConfig, sac_init
    from distributed_cluster_gpus_tpu.rl.train import warm_sac_from_checkpoint
    from distributed_cluster_gpus_tpu.utils.checkpoint import save_checkpoint

    donor_cfg = SACConfig(obs_dim=13, n_dc=2, n_g=4, critic_arch="heads",
                          constraints=default_constraints())
    donor = sac_init(donor_cfg, jax.random.key(7))
    ckpt = str(tmp_path / "wk")
    save_checkpoint(ckpt, step=3, sac=donor)

    tgt_cfg = SACConfig(obs_dim=13, n_dc=2, n_g=4, critic_arch="onehot",
                        constraints=default_constraints())
    warm = warm_sac_from_checkpoint(tgt_cfg, ckpt, jax.random.key(8))
    fresh = sac_init(tgt_cfg, jax.random.key(8))

    for grafted, donor_p in ((warm.actor_params, donor.actor_params),
                             (warm.enc_params, donor.enc_params)):
        for a, b in zip(jax.tree.leaves(grafted), jax.tree.leaves(donor_p)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # critic arch differs from the donor's -> must be the fresh init
    for a, b in zip(jax.tree.leaves(warm.critic_params),
                    jax.tree.leaves(fresh.critic_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(warm.log_alpha) == float(fresh.log_alpha)
    assert int(warm.step) == 0


# ---------------------------------------------------------------------------
# verified store: atomic commit, strict names, fallback, retention (round 12)
# ---------------------------------------------------------------------------

def _tiny():
    return {"a": np.arange(16, dtype=np.int64),
            "b": {"x": np.linspace(0.0, 1.0, 9, dtype=np.float32)}}


def _corrupt_payload(ckpt_dir):
    """Flip bytes in the first manifest-listed payload file."""
    man = json.load(open(os.path.join(ckpt_dir, "manifest.json")))
    rel = sorted(man["files"])[0]
    path = os.path.join(ckpt_dir, rel)
    with open(path, "r+b") as f:
        first = f.read(1)
        f.seek(0)
        f.write(bytes([first[0] ^ 0xFF]))
    return rel


def test_latest_step_strict_name_parsing(tmp_path):
    """`step_5_tmp`-style staging names satisfied the old lenient
    `split("_")[1].isdigit()` parse and were returned as step 5 — the
    strict rule accepts exactly step_<10 digits>."""
    root = str(tmp_path)
    for name in ("step_5", "step_5_tmp", "step_0000000009_tmp",
                 "step_abc", "step_00000003", "stepx_0000000004",
                 "step_0000000003"):
        os.makedirs(os.path.join(root, name))
    assert latest_step(root) == 3
    assert steps(root) == [3]
    # the strict-parsed dir is empty -> not a verifiable checkpoint
    assert latest_step(root, verified=True) is None


def test_save_commits_with_manifest_and_marker(tmp_path):
    root = str(tmp_path)
    d = save_checkpoint(root, 4, metadata={"seed": 11, "chunk": 4}, **_tiny())
    assert d == os.path.join(root, step_dirname(4))
    assert os.path.exists(os.path.join(d, "manifest.json"))
    assert os.path.exists(os.path.join(d, "COMMIT"))
    man = verify_checkpoint(d)
    assert man["schema_version"] == 1
    assert man["trees"] == ["a", "b"]
    assert man["metadata"] == {"seed": 11, "chunk": 4}
    assert man["n_files"] == len(man["files"]) > 0
    # no staging debris after a clean commit
    assert [n for n in os.listdir(root) if n.endswith("_tmp")] == []
    out = restore_checkpoint(root)
    np.testing.assert_array_equal(out["a"], _tiny()["a"])


def test_resave_same_step_is_safe(tmp_path):
    """Overwriting an existing step (done+stop double-save) swaps via a
    never-committed-parseable name and stays verified."""
    root = str(tmp_path)
    save_checkpoint(root, 2, **_tiny())
    t2 = {"a": np.arange(3), "b": {"x": np.zeros(2, np.float32)}}
    save_checkpoint(root, 2, **t2)
    verify_checkpoint(os.path.join(root, step_dirname(2)))
    out = restore_checkpoint(root, 2)
    np.testing.assert_array_equal(out["a"], t2["a"])
    assert steps(root) == [2]


@pytest.mark.parametrize("point", CRASH_POINTS)
def test_crash_injection_store_stays_verified(tmp_path, monkeypatch, point):
    """The acceptance sweep: after a crash at ANY injection point the
    store contains only checkpoints verify_checkpoint accepts, gc
    sweeps the debris, and resume restores the newest verified step."""
    root = str(tmp_path)
    save_checkpoint(root, 1, **_tiny())
    monkeypatch.setenv("DCG_CKPT_CRASH_POINT", point)
    if point == "committed":
        # the crash fires after the rename: the new step IS committed
        with pytest.raises(CheckpointCrashInjected):
            save_checkpoint(root, 2, **_tiny())
        monkeypatch.delenv("DCG_CKPT_CRASH_POINT")
        assert latest_step(root, verified=True) == 2
    else:
        with pytest.raises(CheckpointCrashInjected):
            save_checkpoint(root, 2, **_tiny())
        monkeypatch.delenv("DCG_CKPT_CRASH_POINT")
        # the half-written step is staging debris, never a committed name
        assert steps(root) == [1]
        assert any(n.endswith("_tmp") for n in os.listdir(root))
        assert latest_step(root, verified=True) == 1
    rep = gc_checkpoints(root)
    assert not any(n.endswith("_tmp") for n in os.listdir(root))
    if point != "committed":
        assert rep["swept"], "gc must sweep the stranded staging dir"
    step, out = restore_latest(root)
    assert step == (2 if point == "committed" else 1)
    np.testing.assert_array_equal(out["a"], _tiny()["a"])


def test_restore_fallback_skips_corrupt_newest(tmp_path, caplog):
    """Bit rot on the newest step degrades the restore to the previous
    one with a logged reason instead of crashing."""
    import logging

    root = str(tmp_path)
    save_checkpoint(root, 1, **_tiny())
    t2 = {"a": np.arange(5), "b": {"x": np.ones(2, np.float32)}}
    save_checkpoint(root, 2, **t2)
    _corrupt_payload(os.path.join(root, step_dirname(2)))
    with pytest.raises(CheckpointCorruptError, match="digest mismatch"):
        verify_checkpoint(os.path.join(root, step_dirname(2)))
    with caplog.at_level(logging.WARNING, logger="dcg.checkpoint"):
        assert latest_step(root, verified=True) == 1
        step, out = restore_latest(root)
    assert step == 1
    np.testing.assert_array_equal(out["a"], _tiny()["a"])
    assert any("digest mismatch" in r.message for r in caplog.records)
    # explicit-step restore of the corrupt one refuses loudly
    with pytest.raises(CheckpointCorruptError):
        restore_checkpoint(root, 2)


def test_uncommitted_dir_rejected(tmp_path):
    """A committed-looking dir without manifest/orbax markers (torn by a
    pre-round-12 crash or tampering) fails verification."""
    root = str(tmp_path)
    d = os.path.join(root, step_dirname(7))
    os.makedirs(d)
    open(os.path.join(d, "junk"), "w").write("x")
    with pytest.raises(CheckpointCorruptError, match="uncommitted|no manifest"):
        verify_checkpoint(d)
    assert latest_step(root, verified=True) is None


def test_manifest_newer_schema_refused(tmp_path):
    root = str(tmp_path)
    d = save_checkpoint(root, 1, **_tiny())
    man_path = os.path.join(d, "manifest.json")
    man = json.load(open(man_path))
    man["schema_version"] = 99
    json.dump(man, open(man_path, "w"))
    with pytest.raises(CheckpointCorruptError, match="newer than this reader"):
        verify_checkpoint(d)


def test_gc_retention_keeps_newest_verified(tmp_path):
    root = str(tmp_path)
    for s in (1, 2, 3, 4):
        save_checkpoint(root, s, **_tiny())
    os.makedirs(os.path.join(root, "step_0000000008_tmp"))
    # corrupt the newest: it must NOT count toward the keep budget
    _corrupt_payload(os.path.join(root, step_dirname(4)))
    rep = gc_checkpoints(root, keep=2)
    assert rep["swept"] == ["step_0000000008_tmp"]
    assert rep["pruned"] == [step_dirname(1)]
    assert rep["corrupt"] == [step_dirname(4)]
    assert steps(root) == [2, 3, 4]  # corrupt reported, kept by default
    rep2 = gc_checkpoints(root, keep=2, prune_corrupt=True)
    assert steps(root) == [2, 3]
    assert rep2["corrupt"] == [step_dirname(4)]


def test_metadata_records_run_identity(tmp_path):
    """The trainer-side manifest metadata: seed, params fingerprint,
    chaos stage/reseed, chunk — readable from the store alone."""
    from distributed_cluster_gpus_tpu.fault import ChaosCurriculum
    from distributed_cluster_gpus_tpu.models import FaultParams
    from distributed_cluster_gpus_tpu.rl.train import _ckpt_metadata
    from distributed_cluster_gpus_tpu.utils.checkpoint import (
        config_fingerprint)

    cur = ChaosCurriculum(name="t", mtbf_lo_s=50.0, mtbf_hi_s=100.0
                          ).at_stage(0).reseeded(3)
    params = SimParams(algo="chsac_af", duration=30.0, seed=9,
                       faults=FaultParams(curriculum=cur))
    fleet = object.__new__(object)  # fingerprint treats it as repr(...)
    meta = _ckpt_metadata(fleet, params, config_fingerprint(fleet, params), 5)
    assert meta["seed"] == 9 and meta["chunk"] == 5
    assert meta["chaos"] == {"name": "t", "stage": 0, "reseed": 3}
    assert meta["params_fingerprint"].startswith("sha256:")
    d = save_checkpoint(str(tmp_path), 5, metadata=meta, **_tiny())
    assert verify_checkpoint(d)["metadata"]["chaos"]["reseed"] == 3


def test_config_fingerprint_stable_and_sensitive():
    from distributed_cluster_gpus_tpu.utils.checkpoint import (
        config_fingerprint)

    p1 = SimParams(algo="joint_nf", duration=60.0, seed=4)
    p2 = SimParams(algo="joint_nf", duration=60.0, seed=4)
    p3 = SimParams(algo="joint_nf", duration=60.0, seed=5)
    assert config_fingerprint(p1) == config_fingerprint(p2)
    assert config_fingerprint(p1) != config_fingerprint(p3)
    assert config_fingerprint(np.arange(4)) != config_fingerprint(
        np.arange(4, dtype=np.float32))


def test_warm_sac_fallback_on_corrupt_newest(tmp_path, caplog):
    """chaos_sweep --warm-ckpt resilience: a corrupt newest checkpoint in
    the donor store degrades the policy graft to the previous step with
    a logged warning instead of raising."""
    import logging

    from distributed_cluster_gpus_tpu.rl.train import warm_sac_from_checkpoint

    cfg = SACConfig(obs_dim=13, n_dc=2, n_g=4,
                    constraints=default_constraints())
    donor_old = sac_init(cfg, jax.random.key(3))
    donor_new = sac_init(cfg, jax.random.key(4))
    ckpt = str(tmp_path / "donor")
    save_checkpoint(ckpt, 1, sac=donor_old)
    save_checkpoint(ckpt, 2, sac=donor_new)
    _corrupt_payload(os.path.join(ckpt, step_dirname(2)))
    with caplog.at_level(logging.WARNING, logger="dcg.checkpoint"):
        warm = warm_sac_from_checkpoint(cfg, ckpt, jax.random.key(8))
    assert any("skipping checkpoint" in r.message for r in caplog.records)
    # the graft came from step 1 (the older, intact donor)
    for a, b in zip(jax.tree.leaves(warm.actor_params),
                    jax.tree.leaves(donor_old.actor_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# fsck CLI (scripts/fsck_ckpt.py)
# ---------------------------------------------------------------------------

def test_fsck_clean_store_passes(tmp_path, capsys):
    from scripts.fsck_ckpt import main as fsck_main

    root = str(tmp_path)
    save_checkpoint(root, 1, **_tiny())
    save_checkpoint(root, 2, **_tiny())
    assert fsck_main([root]) == 0
    out = capsys.readouterr().out
    assert out.count("PASS:") == 2
    assert "checkpoint store OK" in out


def test_fsck_flags_corruption_and_debris(tmp_path, capsys):
    from scripts.fsck_ckpt import main as fsck_main

    root = str(tmp_path)
    save_checkpoint(root, 1, **_tiny())
    save_checkpoint(root, 2, **_tiny())
    _corrupt_payload(os.path.join(root, step_dirname(2)))
    os.makedirs(os.path.join(root, "step_0000000009_tmp"))
    os.makedirs(os.path.join(root, "step_5"))  # lenient-name hazard
    assert fsck_main([root]) == 1
    err = capsys.readouterr().err
    assert "digest mismatch" in err
    assert "stranded staging debris" in err
    assert "lenient step-like name" in err
    # --gc sweeps the staging debris; corruption still fails
    assert fsck_main([root, "--gc"]) == 1
    assert not os.path.isdir(os.path.join(root, "step_0000000009_tmp"))


def test_fsck_reads_abort_bundle(tmp_path, capsys):
    from scripts.fsck_ckpt import main as fsck_main

    root = str(tmp_path)
    save_checkpoint(root, 1, **_tiny())
    ab = os.path.join(root, "aborted")
    save_checkpoint(ab, 3, **_tiny())
    json.dump({"kind": "watchdog", "chunk": 3, "probes": ["nonfinite_energy"]},
              open(os.path.join(ab, "abort_context.json"), "w"))
    assert fsck_main([root]) == 0
    out = capsys.readouterr().out
    assert "kind=watchdog" in out
    assert out.count("PASS:") == 3  # step 1, context line, aborted step 3


# ---------------------------------------------------------------------------
# subprocess SIGKILL mid-save (slow tier): the real crash, not an exception
# ---------------------------------------------------------------------------

_KILL_SCRIPT = """
import os, sys
import numpy as np
sys.path.insert(0, {repo!r})
from distributed_cluster_gpus_tpu.utils.checkpoint import save_checkpoint
root = sys.argv[1]
trees = dict(a=np.arange(32), b=dict(x=np.ones((4, 4), np.float32)))
save_checkpoint(root, 1, **trees)
os.environ["DCG_CKPT_CRASH_POINT"] = sys.argv[2]
os.environ["DCG_CKPT_CRASH_MODE"] = "kill"
save_checkpoint(root, 2, **trees)
print("UNREACHABLE")
"""


@pytest.mark.parametrize("point", ["staged", "marker"])
def test_sigkill_mid_save_subprocess(tmp_path, point):
    """e2e: a real SIGKILL mid-save (no Python unwinding, no atexit)
    leaves only the prior verified step + staging debris; gc cleans and
    resume restores step 1."""
    import signal

    repo = os.path.abspath(os.path.join(HERE, os.pardir))
    root = str(tmp_path / "store")
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_SCRIPT.format(repo=repo), root, point],
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, timeout=600)
    assert proc.returncode == -signal.SIGKILL, proc.stderr.decode()
    assert b"UNREACHABLE" not in proc.stdout
    assert steps(root) == [1]
    assert latest_step(root, verified=True) == 1
    debris = [n for n in os.listdir(root) if n.endswith("_tmp")]
    assert debris, "SIGKILL mid-save must strand the staging dir"
    gc_checkpoints(root)
    assert not any(n.endswith("_tmp") for n in os.listdir(root))
    step, out = restore_latest(root)
    assert step == 1
    np.testing.assert_array_equal(out["a"], np.arange(32))


def test_interrupted_resave_swap_recovers(tmp_path):
    """A crash between the re-save swap's two renames must never lose
    the committed step: gc rolls the swap FORWARD when the staging dir
    carries a full commit (manifest + COMMIT), BACK otherwise — before
    the debris sweep can touch either copy."""
    t_old = {"a": np.arange(4), "b": {"x": np.zeros(2, np.float32)}}
    t_new = {"a": np.arange(9), "b": {"x": np.ones(2, np.float32)}}

    def make_interrupted_swap(root, staged_committed):
        """Fabricate the crash window: step_1 renamed away to _swap,
        staging not yet renamed in."""
        save_checkpoint(root, 1, **t_old)
        final = os.path.join(root, step_dirname(1))
        os.rename(final, final + "_swap")
        d = save_checkpoint(root, 1, **t_new)  # the re-save payload...
        os.rename(d, final + "_tmp")  # ...caught pre-rename
        if not staged_committed:
            os.remove(os.path.join(final + "_tmp", "COMMIT"))

    # forward: staging fully committed -> promote the NEW payload
    r1 = str(tmp_path / "fwd")
    make_interrupted_swap(r1, staged_committed=True)
    assert steps(r1) == []  # the crash window: no committed step at all
    rep = gc_checkpoints(r1)
    assert rep["recovered"] and "promoted" in rep["recovered"][0]
    assert latest_step(r1, verified=True) == 1
    np.testing.assert_array_equal(restore_checkpoint(r1, 1)["a"], t_new["a"])
    assert not any(n.endswith(("_tmp", "_swap")) for n in os.listdir(r1))

    # back: staging has no COMMIT marker -> restore the OLD commit
    r2 = str(tmp_path / "back")
    make_interrupted_swap(r2, staged_committed=False)
    rep = gc_checkpoints(r2)
    assert rep["recovered"] and "restored" in rep["recovered"][0]
    assert latest_step(r2, verified=True) == 1
    np.testing.assert_array_equal(restore_checkpoint(r2, 1)["a"], t_old["a"])
    assert not any(n.endswith(("_tmp", "_swap")) for n in os.listdir(r2))

    # stale: the swap completed before the crash -> just swept
    r3 = str(tmp_path / "stale")
    save_checkpoint(r3, 1, **t_old)
    os.makedirs(os.path.join(r3, step_dirname(1) + "_swap"))
    rep = gc_checkpoints(r3)
    assert step_dirname(1) + "_swap" in rep["swept"]
    assert latest_step(r3, verified=True) == 1

"""Chaos-curriculum semantics (fault/curriculum.py + engine composition).

Covers the acceptance properties of the chaos-native-training tentpole:
* curricula validate, lower into sorted fixed-shape timelines, and are
  a pure function of (key, reseed) with independent per-lane draws;
* an all-disabled curriculum is bit-identical to the plain
  enabled-but-empty schedule AND compiles the identical program (the
  curriculum-off pin, same contract as obs_enabled=False);
* severity stages ramp realized incident counts; reseeds re-draw;
* fault x workload composition: a chaos preset under the flash_crowd
  workload keeps every conservation probe clean with valid
  fault_log/cluster_log schemas, and the zero-fault golden holds with
  signal timelines on;
* JSON specs round-trip and the validate_chaos linter catches broken
  ones (tier-1 negative case);
* chaos_sweep cell keying resumes across both sweep axes.
"""

import dataclasses
import filecmp
import importlib.util
import json
import os

import jax
import numpy as np
import pandas as pd
import pytest

from distributed_cluster_gpus_tpu.configs.paper import build_duo_fleet
from distributed_cluster_gpus_tpu.fault import (
    CHAOS_PRESETS, HELD_OUT_PRESETS, ChaosCurriculum, ChaosStage,
    chaos_from_dict, init_fault_state, make_chaos_preset, ramp_stages,
    timeline_len)
from distributed_cluster_gpus_tpu.models import FaultParams, SimParams
from distributed_cluster_gpus_tpu.sim.io import run_simulation

HERE = os.path.dirname(os.path.abspath(__file__))
FREQ = np.asarray((0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0), np.float32)


@pytest.fixture(scope="module")
def duo_fleet():
    """Tiny 2-DC world (fast compiles, the fault/obs suite shape)."""
    return build_duo_fleet()


DUO_KW = dict(
    algo="default_policy", duration=90.0, log_interval=5.0,
    inf_mode="poisson", inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
    job_cap=128, queue_cap=256, seed=11,
)

# a dense tiny curriculum: every family realizes incidents inside a
# 90 s run (rates are per hour, so these are deliberately extreme)
TINY_CUR = ChaosCurriculum(
    name="tiny", mtbf_lo_s=30.0, mtbf_hi_s=120.0,
    mttr_lo_s=10.0, mttr_hi_s=30.0,
    derate_rate_per_dc_hour=80.0, derate_dur_lo_s=5.0, derate_dur_hi_s=20.0,
    wan_rate_per_edge_hour=80.0, wan_dur_lo_s=5.0, wan_dur_hi_s=20.0,
    stages=ramp_stages(3, rate_to=3.0, mttr_to=1.5, severity_to=1.5),
).sized_for(90.0)


def _lower(cur, key=0, n_dc=2, n_ing=2, td=np.float32):
    return init_fault_state(jax.random.key(key),
                            FaultParams(curriculum=cur),
                            n_dc=n_dc, n_ing=n_ing, freq_levels=FREQ,
                            tdtype=td)


# ---------------------------------------------------------------------------
# spec validation + helpers
# ---------------------------------------------------------------------------

def test_curriculum_validation():
    with pytest.raises(ValueError, match="mtbf"):
        ChaosCurriculum(mtbf_lo_s=100.0, mtbf_hi_s=50.0)
    with pytest.raises(ValueError, match="mttr"):
        ChaosCurriculum(mtbf_lo_s=10.0, mtbf_hi_s=20.0, mttr_lo_s=0.0)
    with pytest.raises(ValueError, match="derate_f_hi"):
        ChaosCurriculum(derate_rate_per_dc_hour=1.0, derate_f_hi=1.5)
    with pytest.raises(ValueError, match="wan_mult"):
        ChaosCurriculum(wan_rate_per_edge_hour=1.0, wan_mult_lo=0.5)
    with pytest.raises(ValueError, match="wan_loss_hi"):
        ChaosCurriculum(wan_rate_per_edge_hour=1.0, wan_loss_hi=1.0)
    with pytest.raises(ValueError, match="stage"):
        ChaosCurriculum(stage=1)
    with pytest.raises(ValueError, match="at least one stage"):
        ChaosCurriculum(stages=())
    with pytest.raises(ValueError, match="rate_scale"):
        ChaosStage(rate_scale=0.0)


def test_stage_and_budget_helpers():
    st = ramp_stages(3, rate_to=3.0)
    assert len(st) == 3
    assert st[0].rate_scale == 1.0 and st[2].rate_scale == 3.0
    cur = TINY_CUR.at_stage(2).reseeded(5)
    assert cur.stage == 2 and cur.reseed == 5
    # sized_for covers the expected count with ~3x headroom
    sized = ChaosCurriculum(mtbf_lo_s=30.0, mtbf_hi_s=30.0, mttr_lo_s=10.0,
                            mttr_hi_s=10.0).sized_for(400.0)
    assert sized.max_outages_per_dc >= 3 * 400.0 / 40.0
    # held-out presets exist and are disjoint from the training presets
    for name in HELD_OUT_PRESETS:
        assert name in CHAOS_PRESETS
        assert name.startswith("held_out")
    with pytest.raises(ValueError, match="unknown chaos preset"):
        make_chaos_preset("nope")


def test_curriculum_events_budget_matches_timeline():
    n_dc, n_ing = 2, 2
    fp = FaultParams(curriculum=TINY_CUR)
    M = timeline_len(fp, n_dc, n_ing)
    assert M == 1 + TINY_CUR.n_events(n_dc, n_ing)
    fs = _lower(TINY_CUR)
    t = np.asarray(fs.times)
    assert t.shape == (M,)
    finite = t[np.isfinite(t)]
    assert np.all(np.diff(finite) >= 0), "timeline must be sorted"
    assert int(fs.cursor) == 0
    assert not np.isfinite(t[-1]), "trailing sentinel must be +inf"
    # wan budget needs the ingress count
    with pytest.raises(ValueError, match="n_ing"):
        timeline_len(fp, n_dc)


def test_curriculum_pure_function_of_key_and_reseed():
    a, b = _lower(TINY_CUR, key=3), _lower(TINY_CUR, key=3)
    np.testing.assert_array_equal(np.asarray(a.times), np.asarray(b.times))
    c = _lower(TINY_CUR.reseeded(1), key=3)
    assert not np.array_equal(np.asarray(a.times), np.asarray(c.times)), \
        "reseed must re-draw the realization"


def test_curriculum_lanes_independent_under_vmap():
    keys = jax.random.split(jax.random.key(0), 4)
    fp = FaultParams(curriculum=TINY_CUR)
    fsv = jax.vmap(lambda k: init_fault_state(
        k, fp, n_dc=2, n_ing=2, freq_levels=FREQ,
        tdtype=np.float32))(keys)
    tv = np.asarray(fsv.times)
    for i in range(1, 4):
        assert not np.array_equal(tv[0], tv[i]), (
            "vmapped lanes must realize independent curricula")


def test_curriculum_stage_ramp_realizes_more_incidents():
    def onsets_within(cur, horizon=90.0):
        fs = _lower(cur, key=7)
        t = np.asarray(fs.times)
        kinds = np.asarray(fs.kind)
        return int(((t < horizon) & (kinds >= 0)).sum())

    mild, harsh = TINY_CUR.at_stage(0), TINY_CUR.at_stage(2)
    assert onsets_within(harsh) > onsets_within(mild), (
        "a harsher stage must realize more incidents in-window")


def test_curriculum_off_bit_identical(duo_fleet):
    """The curriculum-off pin (obs_enabled=False style): an all-disabled
    curriculum must lower to the exact empty-schedule FaultState AND
    trace the identical step program as FaultParams() — the chaos knobs
    cannot leak when every family is off."""
    from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

    off = ChaosCurriculum(name="off")  # every family disabled
    fs0 = init_fault_state(jax.random.key(5), FaultParams(), n_dc=2,
                           n_ing=2, freq_levels=FREQ, tdtype=np.float32)
    fs1 = init_fault_state(jax.random.key(5), FaultParams(curriculum=off),
                           n_dc=2, n_ing=2, freq_levels=FREQ,
                           tdtype=np.float32)
    for f in ("times", "kind", "idx", "value"):
        np.testing.assert_array_equal(np.asarray(getattr(fs0, f)),
                                      np.asarray(getattr(fs1, f)))

    def jaxpr_of(fp):
        params = SimParams(faults=fp, **DUO_KW)
        eng = Engine(duo_fleet, params)
        st = init_state(jax.random.key(0), duo_fleet, params)
        return str(jax.make_jaxpr(lambda s: eng._run_chunk(s, None, 8))(st))

    assert jaxpr_of(FaultParams()) == jaxpr_of(FaultParams(curriculum=off)), \
        "an all-off curriculum changed the compiled program"


# ---------------------------------------------------------------------------
# fault x workload composition (PR 8 satellite)
# ---------------------------------------------------------------------------

def test_zero_fault_bit_identical_with_signals_on(duo_fleet, tmp_path):
    """Zero-fault golden with the signal path live: an enabled-but-empty
    schedule under a signal-carrying workload must byte-equal the
    fault-free run (job log exactly; cluster log up to the fault
    columns the fault run appends)."""
    from distributed_cluster_gpus_tpu.workload import make_preset

    wl = make_preset("legacy_signals", duo_fleet,
                     params=SimParams(**DUO_KW))
    runs = {}
    for name, fp in (("off", None), ("empty", FaultParams())):
        params = SimParams(workload=wl, faults=fp, **DUO_KW)
        out = str(tmp_path / name)
        state = run_simulation(duo_fleet, params, out_dir=out,
                               chunk_steps=512)
        runs[name] = (state, out)
    s0, out0 = runs["off"]
    s1, out1 = runs["empty"]
    assert int(s0.n_events) == int(s1.n_events)
    np.testing.assert_array_equal(np.asarray(s0.dc.energy_j),
                                  np.asarray(s1.dc.energy_j))
    np.testing.assert_array_equal(
        np.asarray(s0.signals.cost_usd), np.asarray(s1.signals.cost_usd))
    np.testing.assert_array_equal(
        np.asarray(s0.signals.carbon_g), np.asarray(s1.signals.carbon_g))
    assert filecmp.cmp(out0 + "/job_log.csv", out1 + "/job_log.csv",
                       shallow=False)
    cl0 = pd.read_csv(out0 + "/cluster_log.csv")
    cl1 = pd.read_csv(out1 + "/cluster_log.csv")
    # the fault run interleaves [up, derate_f] before the signal columns;
    # the shared columns must match exactly
    assert set(cl0.columns) | {"up", "derate_f"} == set(cl1.columns)
    for col in cl0.columns:
        np.testing.assert_array_equal(cl0[col].to_numpy(),
                                      cl1[col].to_numpy(), err_msg=col)


def test_chaos_preset_under_flash_crowd_probes_clean(duo_fleet, tmp_path):
    """Fault x workload composition: a dense curriculum under the
    flash_crowd workload (10x arrival spike + carbon signals) must keep
    every conservation/invariant probe clean while realizing incidents,
    with valid fault_log/cluster_log schemas."""
    from distributed_cluster_gpus_tpu.evaluation import fault_metrics
    from distributed_cluster_gpus_tpu.obs.health import split_counts
    from distributed_cluster_gpus_tpu.workload import make_preset

    wl = make_preset("flash_crowd", duo_fleet, base_rate=2.0,
                     horizon_s=90.0, bin_s=15.0)
    params = SimParams(workload=wl, faults=FaultParams(curriculum=TINY_CUR),
                       obs_enabled=True, **DUO_KW)
    out = str(tmp_path / "chaos_flash")
    state = run_simulation(duo_fleet, params, out_dir=out, chunk_steps=512)

    rep = split_counts(np.asarray(state.telemetry.viol))
    assert rep.violation_total == 0, rep.violations
    fm = fault_metrics(duo_fleet, state)
    assert fm["n_outages"] > 0, "tiny curriculum must realize outages"
    assert fm["availability"] < 1.0

    # fault_log schema: every fired transition names a real target
    fl = pd.read_csv(out + "/fault_log.csv")
    assert list(fl.columns) == ["time_s", "event", "target", "value"]
    assert len(fl) > 0
    kinds = set(fl["event"])
    assert kinds <= {"dc_down", "dc_up", "derate", "wan_degrade"}
    assert {"dc_down", "dc_up"} <= kinds
    names = set(duo_fleet.dc_names)
    wan_names = {f"{i}->{d}" for i in duo_fleet.ingress_names
                 for d in duo_fleet.dc_names}
    assert set(fl["target"]) <= names | wan_names
    assert (fl["time_s"].diff().dropna() >= 0).all()

    # cluster_log schema: base + fault + signal columns, sane values
    cl = pd.read_csv(out + "/cluster_log.csv")
    for col in ("up", "derate_f", "price_usd_kwh", "carbon_g_kwh"):
        assert col in cl.columns, col
    assert set(cl["up"]) <= {0, 1}
    assert (cl["carbon_g_kwh"] >= 0).all()
    assert 0 in set(cl["up"]), "outage windows must show up in the log"


# ---------------------------------------------------------------------------
# JSON specs + linter (tier-1 gate incl. negative case)
# ---------------------------------------------------------------------------

def test_chaos_json_roundtrip(tmp_path):
    from distributed_cluster_gpus_tpu.fault import load_chaos_json

    doc = {"name": "spec", "outages": {"mtbf_lo_s": 600, "mtbf_hi_s": 1200,
                                       "mttr_lo_s": 60, "mttr_hi_s": 120,
                                       "max_per_dc": 5},
           "wan": {"rate_per_edge_hour": 2, "dur_lo_s": 30, "dur_hi_s": 60,
                   "mult_lo": 2.0, "mult_hi": 4.0, "loss_hi": 0.1,
                   "max_per_edge": 3},
           "stages": [{"rate_scale": 1.0}, {"rate_scale": 2.0,
                                            "severity_scale": 1.5}]}
    p = tmp_path / "c.json"
    p.write_text(json.dumps(doc))
    cur = load_chaos_json(str(p))
    assert cur.name == "spec" and cur.outages_on and cur.wan_on
    assert not cur.derates_on
    assert cur.max_outages_per_dc == 5 and cur.max_wan_per_edge == 3
    assert len(cur.stages) == 2 and cur.stages[1].severity_scale == 1.5

    with pytest.raises(ValueError, match="unknown top-level"):
        chaos_from_dict({"outage": {}})
    with pytest.raises(ValueError, match="unknown keys"):
        chaos_from_dict({"outages": {"mtbf_lo": 1}})
    with pytest.raises(ValueError, match="missing"):
        chaos_from_dict({"derates": {"dur_lo_s": 5}})
    with pytest.raises(ValueError, match="stages"):
        chaos_from_dict({"outages": {"mtbf_lo_s": 1, "mtbf_hi_s": 2,
                                     "mttr_lo_s": 1, "mttr_hi_s": 2},
                         "stages": [{"rate": 2}]})


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_chaos",
        os.path.join(HERE, os.pardir, "scripts", "validate_chaos.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_validate_chaos_clean_spec(tmp_path):
    mod = _load_validator()
    p = tmp_path / "ok.json"
    p.write_text(json.dumps(
        {"outages": {"mtbf_lo_s": 1800, "mtbf_hi_s": 3600,
                     "mttr_lo_s": 120, "mttr_hi_s": 300}}))
    errs = mod.lint_curriculum(str(p), FREQ, duration=600.0)
    assert errs == [], errs
    assert mod.main([str(p), "--fleet", "single_dc", "--duration",
                     "600"]) == 0


def test_validate_chaos_catches_violations(tmp_path):
    mod = _load_validator()
    # always-down outage regime
    p1 = tmp_path / "down.json"
    p1.write_text(json.dumps(
        {"outages": {"mtbf_lo_s": 60, "mtbf_hi_s": 120,
                     "mttr_lo_s": 600, "mttr_hi_s": 1200}}))
    errs = mod.lint_curriculum(str(p1), FREQ)
    assert any("down more than up" in e for e in errs), errs
    # budget truncation over the requested duration
    p2 = tmp_path / "trunc.json"
    p2.write_text(json.dumps(
        {"outages": {"mtbf_lo_s": 30, "mtbf_hi_s": 60, "mttr_lo_s": 10,
                     "mttr_hi_s": 20, "max_per_dc": 2}}))
    errs = mod.lint_curriculum(str(p2), FREQ, duration=3600.0)
    assert any("truncates" in e for e in errs), errs
    # unparseable spec + nonzero exit
    p3 = tmp_path / "bad.json"
    p3.write_text(json.dumps({"outages": {"mtbf_lo": 1}}))
    assert mod.main([str(p3), "--fleet", "single_dc"]) == 1
    # all-off curriculum needs --allow-empty
    p4 = tmp_path / "empty.json"
    p4.write_text(json.dumps({"name": "nothing"}))
    assert mod.main([str(p4), "--fleet", "single_dc"]) == 1
    assert mod.main([str(p4), "--fleet", "single_dc", "--allow-empty"]) == 0


# ---------------------------------------------------------------------------
# chaos_sweep cell resume (PR 8 satellite): both axes key idempotently
# ---------------------------------------------------------------------------

def _load_sweep():
    spec = importlib.util.spec_from_file_location(
        "chaos_sweep",
        os.path.join(HERE, os.pardir, "scripts", "chaos_sweep.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_sweep_cell_resume_keys(tmp_path):
    mod = _load_sweep()
    rate_row = {"rate": 1.0, "preset": None, "algo": "joint_nf", "x": 1}
    preset_row = {"rate": None, "preset": "held_out_stragglers",
                  "algo": "joint_nf", "x": 2}
    legacy_row = {"rate": 0.5, "algo": "eco_route"}  # pre-PR-8 artifact
    # since round 16 the key also carries seed/duration/mttr (legacy
    # rows fill in the flag-less defaults — tests/test_sweep.py pins
    # both resume directions)
    from distributed_cluster_gpus_tpu.sweep.spec import (
        DEFAULT_DURATION, DEFAULT_MTTR, DEFAULT_SEED)

    tail = (DEFAULT_SEED, DEFAULT_DURATION, DEFAULT_MTTR)
    assert mod.cell_key(rate_row) == (1.0, "joint_nf",
                                      None, None, None, None) + tail
    assert mod.cell_key(preset_row) == (
        "preset:held_out_stragglers",
        "joint_nf", None, None, None, None) + tail
    assert mod.cell_key(legacy_row) == (0.5, "eco_route",
                                        None, None, None, None) + tail
    assert mod.cell_key(rate_row) != mod.cell_key(preset_row)
    # a different workload / stage / warm checkpoint / fleet is a
    # DIFFERENT cell: re-running with those flags must compute, not skip
    assert mod.cell_key({**preset_row, "workload": "flash_crowd"}) \
        != mod.cell_key(preset_row)
    assert mod.cell_key({**preset_row, "stage": 2}) \
        != mod.cell_key(preset_row)
    assert mod.cell_key({**rate_row, "warm_ckpt": "/ck"}) \
        != mod.cell_key(rate_row)
    assert mod.cell_key({**rate_row, "fleet": "duo"}) \
        != mod.cell_key(rate_row)

    # a partial artifact (even with mixed axes) loads into resume keys;
    # a corrupt artifact degrades to an empty resume set
    art = tmp_path / "sweep.json"
    art.write_text(json.dumps({"rows": [rate_row, preset_row, legacy_row]}))
    done = mod.load_done(str(art))
    assert set(done) == {mod.cell_key(r)
                         for r in (rate_row, preset_row, legacy_row)}
    assert done[mod.cell_key(rate_row)]["x"] == 1
    art.write_text("{ not json")
    assert mod.load_done(str(art)) == {}
    assert mod.load_done(str(tmp_path / "missing.json")) == {}

# ---------------------------------------------------------------------------
# held-out chaos sweep e2e (slow tier): chaos-trained policy vs heuristics
# on the three held-out presets, resumable strict-JSON artifact
# ---------------------------------------------------------------------------

def test_held_out_chaos_sweep_e2e(tmp_path, capsys):
    """Acceptance: the held-out sweep scores a chaos-trained CHSAC policy
    (warm-started from a training checkpoint) against >= 2 heuristics on
    the >= 3 held-out presets, composed with the flash_crowd workload,
    writes availability/migration/drop/SLA metrics through the strict-
    JSON writer, and resumes without recomputing finished cells."""
    from distributed_cluster_gpus_tpu.rl.train import train_chsac

    mod = _load_sweep()
    # 1) chaos-train a tiny CHSAC and keep its checkpoint (the "trained
    #    policy" the sweep grafts): same duo world the --tiny axis uses
    duo = mod.tiny_spec(60.0)
    params = dataclasses.replace(
        duo["base"], algo="chsac_af", duration=60.0,
        faults=FaultParams(curriculum=TINY_CUR))
    ck = str(tmp_path / "ck")
    train_chsac(duo["fleet"], params, out_dir=None, chunk_steps=512,
                ckpt_dir=ck, ckpt_every_chunks=1, resume=False)

    # 2) held-out sweep: 3 presets x (2 heuristics + warm chsac)
    art = str(tmp_path / "sweep.json")
    argv = ["--tiny", "--presets", "held_out", "--duration", "60",
            "--algos", "default_policy,joint_nf,chsac_af",
            "--warm-ckpt", ck, "--workload", "flash_crowd",
            "--chunk-steps", "512", "--json", art]
    mod.main(argv)
    doc = json.load(open(art))
    rows = doc["rows"]
    assert len(rows) == 9, [(_r.get("preset"), _r["algo"]) for _r in rows]
    presets = {r["preset"] for r in rows}
    assert presets == set(HELD_OUT_PRESETS)
    for r in rows:
        # availability / migration / drop / SLA metrics in every cell
        for k in ("availability", "n_fault_migrated",
                  "migration_success_rate", "dropped", "p99_lat_inf_s",
                  "completed_inf"):
            assert k in r, (k, sorted(r))
        assert r["workload"] == "flash_crowd"
        assert 0.0 < r["availability"] <= 1.0
    chsac_rows = [r for r in rows if r["algo"] == "chsac_af"]
    assert len(chsac_rows) == 3
    assert all(r["warm_ckpt"] == ck for r in chsac_rows)
    assert all(r.get("train_steps", 0) >= 0 for r in chsac_rows)
    # strict JSON: no bare NaN tokens in the artifact
    raw = open(art).read()
    assert "NaN" not in raw and "Infinity" not in raw

    # 3) resume: a second invocation skips every finished cell
    capsys.readouterr()
    mod.main(argv)
    out = capsys.readouterr().out
    assert out.count("skip") == 9, out

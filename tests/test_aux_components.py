"""Auxiliary component parity: inference LUT, router policy, offline builder."""

import numpy as np
import pandas as pd

from distributed_cluster_gpus_tpu.network import RouterPolicy
from distributed_cluster_gpus_tpu.ops.inference_lut import build_lut, time_and_energy


def test_inference_lut_nearest_lookup():
    lut = build_lut({
        (0.5, 1): (0.010, 2.0), (0.5, 8): (0.004, 1.2),
        (1.0, 1): (0.006, 2.4), (1.0, 8): (0.002, 1.5),
    })
    t, e = time_and_energy(lut, 0.52, 1)
    np.testing.assert_allclose([float(t), float(e)], [0.010, 2.0], rtol=1e-6)
    t, e = time_and_energy(lut, 0.9, 100)  # clamps to nearest keys
    np.testing.assert_allclose([float(t), float(e)], [0.002, 1.5], rtol=1e-6)


def test_router_policy_weights_are_live():
    rp = RouterPolicy(w_latency=1.0, w_queue=0.1)
    lat = np.array([0.02, 0.15])
    q = np.array([10.0, 0.0])
    s = rp.score(lat, 0.0, 0.0, 0.0, q)
    assert s[1] > s[0] or s[1] < s[0]  # deterministic ordering
    # queue weight flips the preference
    rp2 = RouterPolicy(w_latency=1.0, w_queue=1.0)
    assert np.argmin(rp2.score(lat, 0, 0, 0, q)) == 1
    assert np.argmin(RouterPolicy(w_latency=1.0).score(lat, 0, 0, 0, q)) == 0


def test_offline_builder_roundtrip(tmp_path, single_dc_fleet):
    from distributed_cluster_gpus_tpu.models import SimParams
    from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
    from distributed_cluster_gpus_tpu.rl.offline import build_offline_npz_from_logs
    from distributed_cluster_gpus_tpu.rl.replay import load_offline_npz
    from distributed_cluster_gpus_tpu.sim.io import run_simulation

    params = SimParams(algo="joint_nf", duration=40.0, log_interval=5.0,
                       inf_mode="poisson", inf_rate=2.0, trn_mode="off",
                       job_cap=128, seed=6)
    out = str(tmp_path / "run")
    run_simulation(single_dc_fleet, params, out_dir=out, chunk_steps=1024)

    ds = str(tmp_path / "ds.npz")
    n = build_offline_npz_from_logs(out, single_dc_fleet, ds)
    jb = pd.read_csv(out + "/job_log.csv")
    assert n == len(jb) > 10

    rb = load_offline_npz(ds, 4096, [c.name for c in default_constraints()])
    assert int(rb.size) == n
    # reward reconstruction: r = -E_unit_kWh + 0.05/n
    want = (-jb.E_pred / 3.6e6 + 0.05 / jb.n_gpus.clip(lower=1)).to_numpy()
    np.testing.assert_allclose(np.asarray(rb.r[:n]), want, rtol=1e-5)
    # energy_total cost (slot 3) is populated from the cluster log, not zero
    assert float(np.asarray(rb.costs[:n, 3]).max()) > 0.0


def test_package_import_does_not_init_jax_backend():
    """Importing the package (incl. engine/rl CLI import chains) must not
    create device arrays: backend init at import time hangs every CLI
    entry point when the TPU tunnel is wedged (regression: engine.BIG)."""
    import os
    import subprocess
    import sys

    code = (
        "import distributed_cluster_gpus_tpu.rl.train, "
        "distributed_cluster_gpus_tpu.rl.offline, "
        "distributed_cluster_gpus_tpu.sim.engine, "
        "distributed_cluster_gpus_tpu.parallel.rollout\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge._backends, list(xla_bridge._backends)\n"
        "print('no-backend-ok')\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=180, cwd=repo)
    assert out.returncode == 0, out.stderr[-800:]
    assert "no-backend-ok" in out.stdout


def test_route_weighted_uses_policy_weights(fleet):
    import jax.numpy as jnp

    from distributed_cluster_gpus_tpu.sim.algos import route_weighted

    E_grid = jnp.asarray(fleet.E_grid)
    q0 = jnp.zeros((fleet.n_dc,), jnp.int32)
    # pure latency weight: pick the nearest DC to the ingress
    d_lat = int(route_weighted(RouterPolicy(w_latency=1.0), fleet, E_grid,
                               0, 0, 10.0, 0, q0))
    assert d_lat == int(np.argmin(fleet.net_lat_s[0]))
    # pure energy weight: pick the DC with the cheapest best-cell energy
    d_e = int(route_weighted(RouterPolicy(w_latency=0.0, w_energy=1.0), fleet,
                             E_grid, 0, 0, 10.0, 0, q0))
    best_e = np.argmin(fleet.E_grid[:, 0].reshape(fleet.n_dc, -1).min(-1))
    assert d_e == int(best_e)
    # heavy queue penalty steers away from a loaded DC
    q = q0.at[d_e].set(10_000)
    d_q = int(route_weighted(RouterPolicy(w_latency=0.0, w_energy=1.0,
                                          w_queue=1e9), fleet, E_grid,
                             0, 0, 10.0, 0, q))
    assert d_q != d_e


def test_csv_writers_append_mode(tmp_path, single_dc_fleet):
    from distributed_cluster_gpus_tpu.sim.io import CSVWriters

    out = str(tmp_path)
    w1 = CSVWriters(out, single_dc_fleet)
    with open(w1.job_path, "a") as f:
        f.write("sentinel-row\n")
    # append=True must keep existing rows; append=False truncates
    CSVWriters(out, single_dc_fleet, append=True)
    assert "sentinel-row" in open(w1.job_path).read()
    CSVWriters(out, single_dc_fleet, append=False)
    assert "sentinel-row" not in open(w1.job_path).read()


def test_load_run_readafter_cuts_warmup(tmp_path):
    """`readafter` drops pre-cut cluster rows and jobs finishing before the
    cut (reference declares the same knob at plot_sim_result.py:10 without
    applying it; here it is live)."""
    from plot_sim_result import load_run

    pd.DataFrame({
        "time_s": [0.0, 100.0, 200.0, 300.0],
        "power_W": [1.0, 2.0, 3.0, 4.0],
    }).to_csv(tmp_path / "cluster_log.csv", index=False)
    pd.DataFrame({
        "jid": [1, 2, 3],
        "finish_s": [50.0, 150.0, 250.0],
        "latency_s": [0.1, 0.2, 0.3],
    }).to_csv(tmp_path / "job_log.csv", index=False)

    cl, jb = load_run(str(tmp_path))
    assert len(cl) == 4 and len(jb) == 3
    cl, jb = load_run(str(tmp_path), readafter=150.0)
    assert cl["time_s"].tolist() == [200.0, 300.0]
    assert jb["jid"].tolist() == [2, 3]

"""Elastic scaling (chsac_af): preempt-all-training + RL re-placement.

Reference behavior (`simulator_paper_multi.py:330-409, 498-534`): when a
training job finishes while >1 training jobs run, every running training job
is preempted (progress checkpointed) and the policy re-places each one.  Our
fix (SURVEY.md §7.4): a job whose chosen DC is full is queued, not lost.

The test crafts a SimState with three near-done training jobs directly (a
full organic run would need ~300 simulated seconds of training), scans
through the first finish, and asserts the other two were preempted and
re-placed with progress intact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.rl.cmdp import default_constraints
from distributed_cluster_gpus_tpu.rl.sac import SACConfig, make_policy_apply, sac_init
from distributed_cluster_gpus_tpu.sim.engine import Engine, JobStatus, init_state


@pytest.fixture(scope="module")
def elastic_setup(fleet):
    params = SimParams(algo="chsac_af", duration=10_000.0, log_interval=100.0,
                       inf_mode="off", trn_mode="off",
                       elastic_scaling=True, job_cap=32, lat_window=64, seed=0)
    cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
                    n_g=params.max_gpus_per_job, batch=16,
                    constraints=default_constraints())
    sac = sac_init(cfg, jax.random.key(0))
    engine = Engine(fleet, params, policy_apply=make_policy_apply(cfg))
    state = init_state(jax.random.key(1), fleet, params)

    # hand-place 3 running training jobs in DC 0 with different sizes so one
    # finishes first (sizes in work units; T ~ 0.02 s/unit at these coeffs)
    jobs = state.jobs
    for j, (size, n) in enumerate([(100.0, 2), (5000.0, 2), (6000.0, 2)]):
        f_idx = int(state.dc.cur_f_idx[0])
        # hand-placed RUNNING rows must honor the slab contract: cached
        # spu/watts are refreshed wherever (n, f) change (engine._start_job)
        spu, watts = engine._row_TP(jnp.int32(0), jnp.int32(1),
                                    jnp.int32(n), jnp.int32(f_idx))
        jobs = jobs.replace(
            status=jobs.status.at[j].set(JobStatus.RUNNING),
            jtype=jobs.jtype.at[j].set(1),
            dc=jobs.dc.at[j].set(0),
            seq=jobs.seq.at[j].set(j + 1),
            size=jobs.size.at[j].set(size),
            n=jobs.n.at[j].set(n),
            f_idx=jobs.f_idx.at[j].set(f_idx),
            spu=jobs.spu.at[j].set(spu),
            watts=jobs.watts.at[j].set(watts),
            t_start=jobs.t_start.at[j].set(0.001),
        )
    state = state.replace(
        jobs=jobs,
        jid_counter=jnp.int32(4),
        dc=state.dc.replace(busy=state.dc.busy.at[0].set(6)),
    )
    # exactly ONE event: job 0's finish, which triggers the elastic pass
    state, _ = jax.jit(lambda s, p: engine._run_chunk(s, p, 1))(state, sac)
    return state


def test_first_finish_preempts_remaining(elastic_setup):
    state = elastic_setup
    st = np.asarray(state.jobs.status[:3])
    # job 0 finished (slot recycled); jobs 1 and 2 preempted-and-re-placed
    assert st[0] == JobStatus.EMPTY
    assert int(state.n_finished[1]) == 1
    pc = np.asarray(state.jobs.preempt_count[:3])
    assert pc[1] >= 1 and pc[2] >= 1
    # re-placed jobs are running again (or queued if their DC filled)
    assert all(s in (JobStatus.RUNNING, JobStatus.QUEUED) for s in st[1:])


def test_progress_preserved_across_preemption(elastic_setup):
    state = elastic_setup
    # jobs 1/2 had been running ~2s of sim time before the preemption, so
    # they carry nonzero (partial) progress and their original start stamps
    ud = np.asarray(state.jobs.units_done[1:3])
    size = np.asarray(state.jobs.size[1:3])
    assert (ud > 0).all() and (ud < size).all()
    assert (np.asarray(state.jobs.t_start[1:3]) == np.float32(0.001)).all()


def test_cached_physics_after_elastic(elastic_setup, fleet):
    """Resumed jobs' cached spu/watts match recompute — covers the
    preempt -> re-place -> _start_job refresh chain the cap/bandit parity
    test (test_engine.py) does not exercise."""
    from distributed_cluster_gpus_tpu.ops.physics import (step_time_s,
                                                          task_power_w)
    from distributed_cluster_gpus_tpu.models import SimParams as _SP

    state = elastic_setup
    # any algo works for the recompute: coefficients are algo-independent
    eng = Engine(fleet, _SP(algo="joint_nf", duration=10_000.0, job_cap=32,
                            lat_window=64))
    jobs = state.jobs
    pc, tc = eng._job_coeffs(jobs)
    f = eng.freq_levels[jobs.f_idx]
    T = np.asarray(step_time_s(jobs.n, f, tc))
    P = np.asarray(task_power_w(jobs.n, f, pc))
    running = np.asarray(jobs.status) == JobStatus.RUNNING
    assert running.sum() > 0
    np.testing.assert_allclose(np.asarray(jobs.spu)[running], T[running],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(jobs.watts)[running], P[running],
                               rtol=1e-6)


def test_gpu_accounting_consistent(elastic_setup):
    state = elastic_setup
    running = np.asarray(state.jobs.status) == JobStatus.RUNNING
    n = np.asarray(state.jobs.n)
    dc = np.asarray(state.jobs.dc)
    busy = np.asarray(state.dc.busy)
    for d in range(busy.shape[0]):
        assert busy[d] == n[running & (dc == d)].sum()


def test_preempt_notices_logged(tmp_path):
    """Finished-with-preemptions jobs produce project.log notices
    (reference parity: `simulator_paper_multi.py:835,387` logs preempt/
    resume; the scanned engine notices at finish — VERDICT r03 item 7)."""
    import numpy as np

    from distributed_cluster_gpus_tpu.rl.train import (_log_preempt_notices,
                                                       _run_log)
    from distributed_cluster_gpus_tpu.sim.engine import JOB_COLS

    n_steps, n_cols = 6, len(JOB_COLS)
    job = np.zeros((n_steps, n_cols), np.float32)
    valid = np.zeros((n_steps,), bool)
    pc = JOB_COLS.index("preempt_count")
    # one clean finish, one twice-preempted finish
    valid[1] = True; job[1, 0] = 7
    valid[3] = True; job[3, 0] = 9; job[3, pc] = 2; job[3, 4] = 3
    em = {"job_valid": valid, "job": job}
    log = _run_log(str(tmp_path))
    _log_preempt_notices(log, em)
    for h in log.handlers:
        h.flush()
    txt = (tmp_path / "project.log").read_text()
    assert "preempt-resume: job=9 finished after 2 preemption(s) dc=3" in txt
    assert "job=7" not in txt  # clean finishes are not preempt notices


def test_resume_failure_migrates_to_ring(single_dc_fleet):
    """Forced elastic resume failure: the re-placement target has no free
    GPUs for training (inference reserve covers everything the preemption
    freed), so both surviving jobs are QUEUED in the slab by
    `_commit_place(queue_on_full=True)` and the step's post-switch
    `_migrate_elastic_queued` moves them into the DC ring — without any
    `queues.recs` write inside the event switch (VERDICT r04 item 4)."""
    from distributed_cluster_gpus_tpu.models import QRec

    fleet = single_dc_fleet
    total = int(np.asarray(fleet.total_gpus)[0])
    params = SimParams(algo="chsac_af", duration=10_000.0, log_interval=100.0,
                       inf_mode="off", trn_mode="off",
                       elastic_scaling=True, job_cap=16, lat_window=64,
                       seed=0, reserve_inf_gpus=total)  # blocks all training
    cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
                    n_g=params.max_gpus_per_job, batch=16,
                    constraints=default_constraints())
    sac = sac_init(cfg, jax.random.key(0))
    engine = Engine(fleet, params, policy_apply=make_policy_apply(cfg))
    state = init_state(jax.random.key(1), fleet, params)

    jobs = state.jobs
    for j, (size, n) in enumerate([(100.0, 1), (5000.0, 1), (6000.0, 1)]):
        f_idx = int(state.dc.cur_f_idx[0])
        spu, watts = engine._row_TP(jnp.int32(0), jnp.int32(1),
                                    jnp.int32(n), jnp.int32(f_idx))
        jobs = jobs.replace(
            status=jobs.status.at[j].set(JobStatus.RUNNING),
            jtype=jobs.jtype.at[j].set(1),
            dc=jobs.dc.at[j].set(0),
            seq=jobs.seq.at[j].set(j + 1),
            size=jobs.size.at[j].set(size),
            n=jobs.n.at[j].set(n),
            f_idx=jobs.f_idx.at[j].set(f_idx),
            spu=jobs.spu.at[j].set(spu),
            watts=jobs.watts.at[j].set(watts),
            t_start=jobs.t_start.at[j].set(0.001),
        )
    state = state.replace(
        jobs=jobs,
        jid_counter=jnp.int32(4),
        dc=state.dc.replace(busy=state.dc.busy.at[0].set(3)),
    )
    # step 1: job 0 finishes -> elastic preempts jobs 1-2 -> both resume
    # attempts fail (reserve) -> QUEUED -> same-step migration to the ring
    state, _ = jax.jit(lambda s, p: engine._run_chunk(s, p, 1))(state, sac)

    st = np.asarray(state.jobs.status[:3])
    assert st[0] == JobStatus.EMPTY
    # both failures left the slab entirely (migrated, not lingering QUEUED)
    assert (st[1:] == JobStatus.EMPTY).all()
    cnt = np.asarray(state.queues.tail - state.queues.head)
    assert cnt[0, 1] == 2 and cnt.sum() == 2
    # ring records preserve identity and progress; FIFO by seq
    recs = np.asarray(state.queues.recs[0, 1, :2])
    assert recs[0, QRec.SEQ] == 2 and recs[1, QRec.SEQ] == 3
    assert (recs[:, QRec.UNITS_DONE] > 0).all()
    assert (recs[:, QRec.PREEMPT_COUNT] == 1).all()
    # GPUs fully released; queue lengths report the ring contents
    assert int(np.asarray(state.dc.busy)[0]) == 0
    q_inf, q_trn = engine._queue_lens(state)
    assert int(np.asarray(q_trn)[0]) == 2 and int(np.asarray(q_inf)[0]) == 0

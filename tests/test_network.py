"""WAN graph: Dijkstra properties and precomputed matrices."""

import math

import numpy as np
import pytest

from distributed_cluster_gpus_tpu.network import Graph, precompute_net_matrices
from distributed_cluster_gpus_tpu.configs import build_fleet


def test_dijkstra_direct_and_multihop():
    g = Graph()
    g.add_edge("a", "b", 10)
    g.add_edge("b", "c", 5)
    g.add_edge("a", "c", 100)
    lat, path, bn, cost = g.shortest_path_latency("a", "c")
    assert lat == pytest.approx(0.015)  # 15 ms via b
    assert path == ["a", "b", "c"]
    assert bn == 0.0  # infinite capacity convention
    assert cost == 0.0


def test_dijkstra_unreachable():
    g = Graph()
    g.add_edge("a", "b", 10)
    lat, path, bn, cost = g.shortest_path_latency("a", "zzz")
    assert math.isinf(lat)
    assert path == []


def test_dijkstra_bottleneck_and_cost():
    g = Graph()
    g.add_edge("a", "b", 10, capacity_gbps=100.0, cost_per_gb=0.01)
    g.add_edge("b", "c", 5, capacity_gbps=10.0, cost_per_gb=0.02)
    lat, path, bn, cost = g.shortest_path_latency("a", "c")
    assert bn == 10.0
    assert cost == pytest.approx(0.03)


def test_paper_matrices(fleet):
    n_ing, n_dc = len(fleet.ingress_names), len(fleet.dc_names)
    assert fleet.net_lat_s.shape == (n_ing, n_dc)
    assert fleet.transfer_s.shape == (n_ing, n_dc, 2)
    # gw-us-west -> us-west is a direct 12 ms edge
    i = fleet.ingress_names.index("gw-us-west")
    d = fleet.dc_names.index("us-west")
    assert fleet.net_lat_s[i, d] == pytest.approx(0.012)
    # all capacities are infinite -> transfer time equals latency for both jtypes
    np.testing.assert_allclose(fleet.transfer_s[i, d], [0.012, 0.012], rtol=1e-6)
    # every ingress reaches every DC (connected paper WAN)
    assert np.isfinite(fleet.net_lat_s).all()
    # multihop: gw-us-west -> eu-west must route through intermediate nodes
    d2 = fleet.dc_names.index("eu-west")
    assert fleet.net_lat_s[i, d2] > 0.012


def test_fleet_shapes_and_constants(fleet):
    assert len(fleet.dc_names) == 8
    assert len(fleet.ingress_names) == 8
    assert int(fleet.total_gpus.sum()) == 1488
    assert fleet.freq_levels.tolist() == pytest.approx([0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0])
    assert fleet.E_grid.shape == (8, 2, 8, 8)
    # carbon only for 3 DCs
    assert (fleet.carbon > 0).sum() == 3
    # price map: 24 hours, peak pricing midday
    assert fleet.price_hourly.shape == (24,)
    assert fleet.price_hourly[3] == pytest.approx(0.12)
    assert fleet.price_hourly[12] == pytest.approx(0.20)
    assert fleet.price_hourly[20] == pytest.approx(0.16)


def test_validators_clean_fleet(fleet):
    from distributed_cluster_gpus_tpu.utils import validate_gpus

    assert validate_gpus(fleet) == []


def test_validators_flag_bad_config(fleet):
    import dataclasses

    from distributed_cluster_gpus_tpu.utils import validate_gpus

    bad = dataclasses.replace(
        fleet,
        p_sleep=fleet.p_idle + 100.0,  # sleep > idle everywhere
        gpu_alpha=np.full_like(fleet.gpu_alpha, 9.0),
    )
    msgs = validate_gpus(bad)
    assert any("p_sleep" in m for m in msgs)
    assert any("alpha" in m for m in msgs)
    with pytest.raises(ValueError):
        validate_gpus(bad, strict=True)


def test_bandit_ucb1():
    import jax.numpy as jnp

    from distributed_cluster_gpus_tpu.ops.bandit import (
        bandit_init,
        bandit_select,
        bandit_update,
    )

    st = bandit_init(2, 2, 4)
    # explore phase: arms in freq order
    picked = []
    for _ in range(4):
        st, f = bandit_select(st, 0, 0)
        picked.append(int(f))
        st = bandit_update(st, 0, 0, f, cost_per_unit=float(f) + 1.0)  # arm 0 cheapest
    assert picked == [0, 1, 2, 3]
    # exploitation: arm 0 has the best mean reward; UCB eventually prefers it
    counts = [0, 0, 0, 0]
    for _ in range(60):
        st, f = bandit_select(st, 0, 0)
        counts[int(f)] += 1
        st = bandit_update(st, 0, 0, f, cost_per_unit=float(f) + 1.0)
    assert counts[0] == max(counts)

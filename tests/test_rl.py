"""RL stack tests: replay scatter/sample, CMDP PID response, SAC update
finiteness, masked action validity, and a short online-training smoke run.

Model: SURVEY.md §4's designed strategy — (d) RL smoke tests: loss finite,
lambda responds monotonically to injected constraint violation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.rl.cmdp import (
    ConstraintSpec, N_COSTS, cmdp_init, default_constraints, effective_reward,
    update_lagrange,
)
from distributed_cluster_gpus_tpu.rl.replay import (
    load_offline_npz, replay_add_chunk, replay_init, replay_sample,
    save_offline_npz,
)
from distributed_cluster_gpus_tpu.rl.sac import (
    SACConfig, make_policy_apply, sac_init, sac_train_step,
)


def small_cfg(n_dc=3, n_g=4, obs_dim=19, batch=16):
    return SACConfig(obs_dim=obs_dim, n_dc=n_dc, n_g=n_g, batch=batch,
                     n_quantiles=8, latent=32,
                     constraints=default_constraints(500.0))


def fake_chunk(key, n, obs_dim=19, n_dc=3, n_g=4, p_valid=0.5):
    ks = jax.random.split(key, 8)
    return {
        "valid": jax.random.uniform(ks[0], (n,)) < p_valid,
        "s0": jax.random.normal(ks[1], (n, obs_dim)),
        "s1": jax.random.normal(ks[2], (n, obs_dim)),
        "a_dc": jax.random.randint(ks[3], (n,), 0, n_dc),
        "a_g": jax.random.randint(ks[4], (n,), 0, n_g),
        "r": jax.random.normal(ks[5], (n,)),
        "costs": jnp.abs(jax.random.normal(ks[6], (n, N_COSTS))),
        "mask_dc": jnp.ones((n, n_dc), bool),
        "mask_g": jnp.ones((n, n_g), bool),
    }


class TestReplay:
    def test_scatter_only_valid(self):
        rb = replay_init(64, 19, 3, 4, N_COSTS)
        tr = fake_chunk(jax.random.key(0), 40)
        rb = replay_add_chunk(rb, tr)
        n_valid = int(np.sum(np.asarray(tr["valid"])))
        assert int(rb.size) == n_valid
        # rows land compacted in insertion order
        want = np.asarray(tr["r"])[np.asarray(tr["valid"])]
        np.testing.assert_allclose(np.asarray(rb.r[:n_valid]), want)

    def test_ring_wrap(self):
        # C=16, chunks of 10 fully-valid rows: the ingest window scales to
        # C//4 = 4 so a small ring keeps most rows live across wraps — the
        # whole newest chunk must be resident and the invariants must hold
        rb = replay_init(16, 19, 3, 4, N_COSTS)
        last = None
        for i in range(5):
            last = fake_chunk(jax.random.key(i), 10, p_valid=1.0)
            rb = replay_add_chunk(rb, last)
        assert int(rb.size) >= 10
        assert int(np.sum(np.asarray(rb.valid))) == int(rb.size)
        stored = {np.float32(v).tobytes()
                  for v in np.asarray(rb.r)[np.asarray(rb.valid)]}
        assert all(np.float32(v).tobytes() in stored
                   for v in np.asarray(last["r"]))

    def test_mixed_validity_ring_invariants(self):
        # size == valid.sum() must hold through arbitrary ingest sequences,
        # and every valid row must hold a real transition (r values seen)
        rb = replay_init(32, 19, 3, 4, N_COSTS)
        seen = set()  # exact float32 bytes of every real transition's reward
        for i in range(12):
            tr = fake_chunk(jax.random.key(100 + i), 7, p_valid=0.5)
            for v in np.asarray(tr["r"])[np.asarray(tr["valid"])]:
                seen.add(np.float32(v).tobytes())
            rb = replay_add_chunk(rb, tr)
            assert int(rb.size) == int(np.sum(np.asarray(rb.valid)))
        stored = np.asarray(rb.r)[np.asarray(rb.valid)]
        assert all(np.float32(v).tobytes() in seen for v in stored)
        # sampling only ever returns valid rows' contents
        b = replay_sample(rb, jax.random.key(9), 64)
        assert all(np.float32(v).tobytes() in seen for v in np.asarray(b["r"]))

    def test_scatter_mode_invariants(self, monkeypatch):
        """The DCG_REPLAY_INGEST=scatter A/B path keeps the same
        valid/n_seen/sampling semantics as the default slot-ring."""
        from distributed_cluster_gpus_tpu.rl import replay as rp

        monkeypatch.setattr(rp, "INGEST_MODE", "scatter")
        rb = rp.replay_init(32, 19, 3, 4, N_COSTS)
        seen = set()
        total = 0
        for i in range(8):
            tr = fake_chunk(jax.random.key(200 + i), 10, p_valid=0.5)
            sel = np.asarray(tr["valid"])
            total += int(sel.sum())
            for v in np.asarray(tr["r"])[sel]:
                seen.add(np.float32(v).tobytes())
            rb = rp.replay_add_chunk(rb, tr)
            assert int(rb.size) == int(np.sum(np.asarray(rb.valid)))
        assert int(rb.n_seen) == total
        b = rp.replay_sample(rb, jax.random.key(9), 64)
        assert all(np.float32(v).tobytes() in seen
                   for v in np.asarray(b["r"]))

    def test_warmup_gate_survives_ring_plateau(self):
        """size can plateau below capacity (garbage tails from sparse
        windows), so warmup must gate on the monotone n_seen or it would
        deadlock forever."""
        rb = replay_init(64, 19, 3, 4, N_COSTS)
        warmup = 60
        # sparse chunks: each 16-row window stores few valid rows but
        # still claims the window, so `size` stays well below capacity.
        # 14 chunks, not 8: 8 x 48 x 0.15 put the EXPECTED valid count
        # (57.6) below the 60-row warmup this asserts crosses — the fixed
        # seed happened to draw 46 and the assert failed deterministically
        # (pre-round-7 latent failure; slow tier, so rarely run)
        for i in range(14):
            rb = replay_add_chunk(rb, fake_chunk(jax.random.key(i), 48,
                                                 p_valid=0.15))
        assert int(rb.size) < warmup  # the plateau that trapped a size gate
        assert int(rb.size) == int(np.sum(np.asarray(rb.valid)))
        assert int(rb.n_seen) >= warmup  # the monotone gate opens anyway

    def test_sample_shapes_and_range(self):
        rb = replay_init(64, 19, 3, 4, N_COSTS)
        rb = replay_add_chunk(rb, fake_chunk(jax.random.key(1), 40, p_valid=1.0))
        b = replay_sample(rb, jax.random.key(2), 8)
        assert b["s0"].shape == (8, 19)
        assert b["costs"].shape == (8, N_COSTS)

    def test_offline_npz_roundtrip(self, tmp_path):
        rb = replay_init(64, 19, 3, 4, N_COSTS)
        rb = replay_add_chunk(rb, fake_chunk(jax.random.key(3), 30, p_valid=1.0))
        names = [c.name for c in default_constraints()]
        path = str(tmp_path / "ds.npz")
        save_offline_npz(rb, path, names)
        rb2 = load_offline_npz(path, 64, names)
        assert int(rb2.size) == 30
        np.testing.assert_allclose(np.asarray(rb2.costs[:30]),
                                   np.asarray(rb.costs[:30]))

    def test_offline_npz_reference_obs_keys(self, tmp_path):
        # datasets written with the reference's s/s_next spelling must load
        rb = replay_init(64, 19, 3, 4, N_COSTS)
        rb = replay_add_chunk(rb, fake_chunk(jax.random.key(3), 20, p_valid=1.0))
        names = [c.name for c in default_constraints()]
        path = str(tmp_path / "ds.npz")
        save_offline_npz(rb, path, names)
        with np.load(path) as z:
            renamed = {("s" if k == "s0" else "s_next" if k == "s1" else k): v
                       for k, v in z.items()}
        path2 = str(tmp_path / "ds_ref.npz")
        np.savez_compressed(path2, **renamed)
        rb2 = load_offline_npz(path2, 64, names)
        assert int(rb2.size) == 20
        np.testing.assert_allclose(np.asarray(rb2.s0[:20]),
                                   np.asarray(rb.s0[:20]))
        np.testing.assert_allclose(np.asarray(rb2.s1[:20]),
                                   np.asarray(rb.s1[:20]))

    def test_capacity_guard(self):
        with pytest.raises(ValueError, match="2\\^24"):
            replay_init((1 << 24) + 1, 19, 3, 4, N_COSTS)

    def test_offline_npz_minimal_reference_schema(self, tmp_path):
        # masks / costs / done are optional in the reference schema; a
        # dataset with only the required keys must load with all-valid
        # masks, zero costs, done=1 (given explicit action-space dims)
        n, od = 12, 19
        path = str(tmp_path / "min.npz")
        np.savez_compressed(
            path,
            s=np.random.randn(n, od).astype(np.float32),
            s_next=np.random.randn(n, od).astype(np.float32),
            a_dc=np.zeros(n, np.int32), a_g=np.zeros(n, np.int32),
            r=np.ones(n, np.float32))
        names = [c.name for c in default_constraints()]
        rb = load_offline_npz(path, 64, names, n_dc=3, n_g=4)
        assert int(rb.size) == n
        assert bool(np.asarray(rb.mask_dc)[:n].all())
        assert float(np.asarray(rb.costs)[:n].sum()) == 0.0
        assert bool((np.asarray(rb.done)[:n] == 1.0).all())
        with pytest.raises(ValueError, match="n_dc"):
            load_offline_npz(path, 64, names)


class TestCMDP:
    def test_effective_reward(self):
        r = jnp.asarray([1.0, 1.0])
        costs = jnp.asarray([[600.0], [400.0]])
        lam = jnp.asarray([0.1])
        tgt = jnp.asarray([500.0])
        out = effective_reward(r, costs, lam, tgt)
        np.testing.assert_allclose(np.asarray(out), [1.0 - 0.1 * 100.0, 1.0])

    def test_lambda_monotone_under_violation(self):
        """Sustained violation must ramp lambda up (PID integral term)."""
        cons = (ConstraintSpec("latency_p99", 500.0),)
        st = cmdp_init(cons)
        lams = []
        costs = jnp.full((8, 1), 510.0)  # persistent small violation
        for _ in range(20):
            st, _ = update_lagrange(st, cons, costs)
            lams.append(float(st.lam[0]))
        assert all(b >= a for a, b in zip(lams, lams[1:]))
        assert lams[-1] > lams[0]
        # and decays back toward 0 once satisfied (integral is frozen at
        # err=0 so lambda falls to ki*integral level, clamped >= 0)
        st2, _ = update_lagrange(st, cons, jnp.zeros((8, 1)))
        assert float(st2.lam[0]) <= lams[-1]

    def test_lambda_clamped(self):
        cons = (ConstraintSpec("x", 0.0, kp=100.0, lambda_max=10.0),)
        st = cmdp_init(cons)
        st, _ = update_lagrange(st, cons, jnp.full((4, 1), 1e9))
        assert float(st.lam[0]) == 10.0


class TestSAC:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = small_cfg()
        sac = sac_init(cfg, jax.random.key(0))
        rb = replay_init(256, cfg.obs_dim, cfg.n_dc, cfg.n_g, N_COSTS)
        rb = replay_add_chunk(rb, fake_chunk(jax.random.key(1), 128, p_valid=1.0))
        return cfg, sac, rb

    def test_update_finite_and_advances(self, setup):
        cfg, sac, rb = setup
        sac2, m = jax.jit(lambda s, r, k: sac_train_step(cfg, s, r, k))(
            sac, rb, jax.random.key(2))
        for k in ("critic_loss", "actor_loss", "alpha_loss", "entropy", "q_mean"):
            assert np.isfinite(float(m[k])), k
        assert int(sac2.step) == 1
        # params actually moved
        diff = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                            sac.critic_params, sac2.critic_params)
        assert max(jax.tree.leaves(diff)) > 0

    def test_target_polyak_lag(self, setup):
        cfg, sac, rb = setup
        sac2, _ = sac_train_step(cfg, sac, rb, jax.random.key(2))
        # target moved tau-fraction toward online
        d_online = jax.tree.leaves(jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            sac.critic_params, sac2.critic_params))
        d_target = jax.tree.leaves(jax.tree.map(
            lambda a, b: float(jnp.max(jnp.abs(a - b))),
            sac.target_critic_params, sac2.target_critic_params))
        assert max(d_target) < max(d_online)
        assert max(d_target) > 0

    def test_masked_actions_never_selected(self, setup):
        cfg, sac, _ = setup
        pa = make_policy_apply(cfg)
        mask_dc = jnp.asarray([False, True, False])
        mask_g = jnp.asarray([True, False, False, False])
        for i in range(20):
            a_dc, a_g = pa(sac, jnp.zeros(cfg.obs_dim), mask_dc, mask_g,
                           jax.random.key(i))
            assert int(a_dc) == 1
            assert int(a_g) == 0

    def test_lambda_raises_effective_penalty(self, setup):
        """Inject huge latency cost: after updates lambda_latency > 0."""
        cfg, sac, rb = setup
        rb = rb.replace(costs=rb.costs.at[:, 0].set(5000.0))  # p99 ms >> 500
        for i in range(5):
            sac, m = sac_train_step(cfg, sac, rb, jax.random.key(i))
        assert float(m["lambda"][0]) > 0


class TestSACHeadsCritic:
    """The opt-in heads critic (critic_arch="heads") must train like the
    default: finite losses, params move, targets lag, masks respected."""

    @pytest.fixture(scope="class")
    def setup(self):
        cfg = SACConfig(obs_dim=19, n_dc=3, n_g=4, batch=16,
                        n_quantiles=8, latent=32, critic_arch="heads",
                        constraints=default_constraints(500.0))
        sac = sac_init(cfg, jax.random.key(0))
        rb = replay_init(256, cfg.obs_dim, cfg.n_dc, cfg.n_g, N_COSTS)
        rb = replay_add_chunk(rb, fake_chunk(jax.random.key(1), 128, p_valid=1.0))
        return cfg, sac, rb

    def test_update_finite_and_advances(self, setup):
        cfg, sac, rb = setup
        sac2, m = jax.jit(lambda s, r, k: sac_train_step(cfg, s, r, k))(
            sac, rb, jax.random.key(2))
        for k in ("critic_loss", "actor_loss", "alpha_loss", "entropy", "q_mean"):
            assert np.isfinite(float(m[k])), k
        assert int(sac2.step) == 1
        diff = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                            sac.critic_params, sac2.critic_params)
        assert max(jax.tree.leaves(diff)) > 0

    def test_taken_action_matches_all_actions_gather(self, setup):
        """__call__ (taken action) must agree with the all_actions table."""
        from distributed_cluster_gpus_tpu.rl.nets import QuantileCriticHeads

        cfg, sac, rb = setup
        critic = QuantileCriticHeads(n_dc=cfg.n_dc, n_g=cfg.n_g,
                                     n_quantiles=cfg.n_quantiles)
        lat = jax.random.normal(jax.random.key(3), (5, cfg.latent))
        a_dc = jnp.asarray([0, 1, 2, 1, 0])
        a_g = jnp.asarray([3, 0, 1, 2, 0])
        q_taken = critic.apply(sac.critic_params, lat, a_dc, a_g)
        q_all = critic.apply(sac.critic_params, lat,
                             method=critic.all_actions)
        want = q_all[jnp.arange(5), :, a_dc * cfg.n_g + a_g, :]
        np.testing.assert_allclose(np.asarray(q_taken), np.asarray(want))


class TestOfflineTraining:
    def test_pretrain_from_npz(self, tmp_path):
        """save_offline_npz -> train_offline: updates run, losses finite,
        and a dataset smaller than warmup lowers the warmup instead of
        silently doing nothing."""
        from distributed_cluster_gpus_tpu.rl.agent import CHSAC_AF
        from distributed_cluster_gpus_tpu.rl.cmdp import COST_NAMES
        from distributed_cluster_gpus_tpu.rl.train import train_offline

        rb = replay_init(256, 19, 3, 4, N_COSTS)
        rb = replay_add_chunk(rb, fake_chunk(jax.random.key(5), 96, p_valid=1.0))
        path = str(tmp_path / "offline.npz")
        save_offline_npz(rb, path, list(COST_NAMES))

        agent = CHSAC_AF(obs_dim=19, n_dc=3, n_g_choices=4,
                         buffer_capacity=512, batch=16, warmup=1000, seed=3)
        m = train_offline(agent, path, steps=12)
        assert agent.warmup == 96  # lowered to dataset size
        assert int(agent.sac.step) == 12
        assert np.isfinite(float(m["critic_loss"]))


class TestOnlineTraining:
    def test_short_chsac_run_trains(self, single_dc_fleet, tmp_path):
        from distributed_cluster_gpus_tpu.rl.train import train_chsac

        params = SimParams(algo="chsac_af", duration=60.0, log_interval=5.0,
                           inf_mode="poisson", inf_rate=3.0, trn_mode="off",
                           rl_warmup=32, rl_batch=32, job_cap=128, seed=11)
        state, agent, hist = train_chsac(
            single_dc_fleet, params, out_dir=str(tmp_path / "rl"),
            chunk_steps=512, max_train_steps_per_chunk=8)
        assert bool(state.done)
        assert int(agent.sac.step) > 0
        assert len(hist) > 0
        assert np.isfinite(hist[-1]["critic_loss"])
        # transitions carry real masks (at least one valid row ingested)
        assert int(agent.replay.size) >= 32
        # in-run RL metric lines land in project.log (reference parity:
        # `simulator_paper_multi.py:755,807` logs per train call; fused
        # chunks log one line per train chunk — VERDICT r03 item 7)
        logtxt = (tmp_path / "rl" / "project.log").read_text()
        assert "rl-update chunk=" in logtxt
        assert "critic_loss=" in logtxt and "lambda=" in logtxt


def test_windowed_percentile_matches_numpy():
    """Exact np.percentile parity over every fill level (guards the top_k
    formulation and any future reimplementation of the hot op)."""
    from distributed_cluster_gpus_tpu.sim.algos import windowed_percentile

    rng = np.random.default_rng(0)
    for W in (64, 512):
        for m in (1, 3, 5, 17, W // 2, W):
            buf = rng.exponential(1.0, W).astype(np.float32)
            got = float(windowed_percentile(jnp.asarray(buf), jnp.int32(m), 99.0))
            want = float(np.percentile(buf[:m], 99.0))
            assert abs(got - want) <= 1e-4 * max(1.0, abs(want)), (W, m, got, want)


class TestPolicyTail:
    """Invariants of the step's shared policy tail (engine._policy_tail).

    chsac_af arrivals are written to the slab with placeholder
    dc/t_avail=inf and must be routed by the tail WITHIN the same step —
    so between any two steps no XFER job may carry a non-finite t_avail,
    and a routed job's dc must equal its recorded action rl_a_dc.
    """

    def test_deferred_route_commits_same_step(self, fleet):
        from distributed_cluster_gpus_tpu.models import JobStatus
        from distributed_cluster_gpus_tpu.rl.cmdp import constraints_from_params
        from distributed_cluster_gpus_tpu.rl.sac import (
            SACConfig, make_policy_apply, sac_init)
        from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

        params = SimParams(algo="chsac_af", duration=1e9, log_interval=5.0,
                           inf_mode="poisson", inf_rate=8.0,
                           trn_mode="poisson", trn_rate=0.2,
                           job_cap=64, lat_window=64, seed=3)
        cfg = SACConfig(obs_dim=params.obs_dim(fleet.n_dc), n_dc=fleet.n_dc,
                        n_g=params.max_gpus_per_job, batch=16,
                        constraints=constraints_from_params(params))
        eng = Engine(fleet, params, policy_apply=make_policy_apply(cfg))
        pp = sac_init(cfg, jax.random.key(0))
        state = init_state(jax.random.key(1), fleet, params)

        step1 = jax.jit(lambda s: eng._run_chunk(s, pp, 1)[0])
        n_xfer_seen = 0
        for _ in range(400):
            state = step1(state)
            jobs = state.jobs
            xfer = np.asarray(jobs.status) == JobStatus.XFER
            n_xfer_seen += int(xfer.sum())
            # every in-flight transfer has a committed (finite) arrival time
            assert np.isfinite(np.asarray(jobs.t_avail)[xfer]).all()
            # routed jobs run/queue/transfer at the DC the policy chose
            live = np.asarray(jobs.status) != JobStatus.EMPTY
            rl = np.asarray(jobs.rl_valid) & live
            np.testing.assert_array_equal(
                np.asarray(jobs.dc)[rl], np.asarray(jobs.rl_a_dc)[rl])
        assert n_xfer_seen > 50  # the invariant was actually exercised
        assert int(state.jid_counter) > 100


class TestAlphaCap:
    def test_alpha_max_caps_temperature(self):
        """With a constraint-saturated reward the temperature chases an
        unreachable entropy floor and grows unboundedly (canonical week
        run finding); alpha_max must clamp the learned temperature."""
        from distributed_cluster_gpus_tpu.rl.replay import (
            replay_add_chunk, replay_init)
        from distributed_cluster_gpus_tpu.rl.sac import (
            SACConfig, sac_init, sac_train_step)

        # start ABOVE the cap: Adam moves log_alpha by ~lr/step, so a
        # below-cap start could never reach 1.0 in 50 steps and the test
        # would pass with the clamp deleted
        cfg = SACConfig(obs_dim=19, n_dc=3, n_g=4, batch=32,
                        n_quantiles=8, latent=32, alpha_init=5.0,
                        alpha_max=1.0,
                        constraints=default_constraints(500.0))
        sac = sac_init(cfg, jax.random.key(0))
        rb = replay_init(512, 19, 3, 4, N_COSTS)
        tr = fake_chunk(jax.random.key(1), 256, p_valid=1.0)
        # huge latency cost >> target: saturated constraint regime
        tr["costs"] = tr["costs"].at[:, 0].set(3.6e6)
        rb = replay_add_chunk(rb, tr)
        step = jax.jit(lambda s, k: sac_train_step(cfg, s, rb, k))
        sac, m = step(sac, jax.random.key(2))
        # first update already clamps the over-cap start down to the cap
        assert float(jnp.exp(sac.log_alpha)) <= 1.0 + 1e-5
        for i in range(20):
            sac, m = step(sac, jax.random.key(3 + i))
        assert float(jnp.exp(sac.log_alpha)) <= 1.0 + 1e-5
        assert np.isfinite(float(m["critic_loss"]))
        with pytest.raises(AssertionError, match="alpha_max"):
            SACConfig(obs_dim=19, n_dc=3, n_g=4,
                      constraints=default_constraints(500.0), alpha_max=0.0)

    def test_default_config_bounds_alpha(self):
        """Round-4 regression (VERDICT item 5): the DEFAULT temperature law
        is bounded — alpha_max ships as 10.0, so the canonical week's
        alpha -> 2.3e7 runaway (and the near-uniform policy it forces)
        cannot recur in a default-config run."""
        from distributed_cluster_gpus_tpu.rl.replay import (
            replay_add_chunk, replay_init)
        from distributed_cluster_gpus_tpu.rl.sac import (
            SACConfig, sac_init, sac_train_step)

        cfg = SACConfig(obs_dim=19, n_dc=3, n_g=4, batch=32,
                        n_quantiles=8, latent=32,
                        constraints=default_constraints(500.0))
        assert cfg.alpha_max == 10.0  # the defended default, not None
        sac = sac_init(cfg, jax.random.key(0))
        rb = replay_init(512, 19, 3, 4, N_COSTS)
        tr = fake_chunk(jax.random.key(1), 256, p_valid=1.0)
        tr["costs"] = tr["costs"].at[:, 0].set(3.6e6)  # saturated regime
        rb = replay_add_chunk(rb, tr)
        step = jax.jit(lambda s, k: sac_train_step(cfg, s, rb, k))
        for i in range(25):
            sac, m = step(sac, jax.random.key(2 + i))
        assert float(jnp.exp(sac.log_alpha)) <= 10.0 + 1e-4
        assert np.isfinite(float(m["critic_loss"]))

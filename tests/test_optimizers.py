"""Grid optimizers vs. brute-force Python reimplementation of the formulas."""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.ops.optimizers import (
    OBJ_CARBON,
    OBJ_COST,
    OBJ_ENERGY,
    best_energy_freq_idx,
    best_nf_grid,
    min_n_for_sla,
    nf_energy_table,
)
from distributed_cluster_gpus_tpu.ops.physics import LatencyCoeffs, PowerCoeffs

FREQS = np.asarray([0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0], dtype=np.float32)
PC = PowerCoeffs(jnp.float32(75.0), jnp.float32(80.0), jnp.float32(110.0))
TC = LatencyCoeffs(jnp.float32(0.0045), jnp.float32(0.032), jnp.float32(0.0012))


def brute_T(n, f):
    base = 0.0045 + 0.032 / f
    return base if n == 1 else (base + 0.0012 * n) / n


def brute_P(n, f):
    return n * (75.0 * f**3 + 80.0 * f + 110.0)


def brute_best_nf(n_max, score_fn, deadline=None):
    best = None
    for n in range(1, n_max + 1):
        for f in FREQS:
            T = brute_T(n, float(f))
            if deadline is not None and T > deadline:
                continue
            cand = (score_fn(T, brute_P(n, float(f))), n, float(f))
            if best is None or cand[0] < best[0]:  # strict < : first min wins
                best = cand
    return best


@pytest.fixture(scope="module")
def grids():
    T, P, E = nf_energy_table(8, FREQS, PC, TC)
    return np.asarray(T), np.asarray(P), np.asarray(E)


def test_nf_energy_table_matches_brute_force(grids):
    T, P, E = grids
    for n, fi in itertools.product(range(1, 9), range(len(FREQS))):
        f = float(FREQS[fi])
        assert T[n - 1, fi] == pytest.approx(brute_T(n, f), rel=1e-5)
        assert P[n - 1, fi] == pytest.approx(brute_P(n, f), rel=1e-5)
        assert E[n - 1, fi] == pytest.approx(brute_T(n, f) * brute_P(n, f), rel=1e-5)


def test_best_energy_freq(grids):
    for n in (1, 4, 8):
        idx = int(best_energy_freq_idx(n, FREQS, PC, TC))
        energies = [brute_T(n, float(f)) * brute_P(n, float(f)) for f in FREQS]
        assert idx == int(np.argmin(energies))


def test_best_nf_grid_energy(grids):
    _, _, E = grids
    T, _, _ = grids
    n, fi = best_nf_grid(jnp.asarray(E), jnp.asarray(T), OBJ_ENERGY)
    _, bn, bf = brute_best_nf(8, lambda T, P: T * P)
    assert int(n) == bn
    assert float(FREQS[int(fi)]) == pytest.approx(bf)


def test_best_nf_grid_carbon_zero_ci_ties_to_first(grids):
    # Reference quirk: CI == 0 makes every candidate score 0.0, and the strict
    # `<` scan keeps the FIRST candidate: n=1, f=freq_levels[0].
    T, _, E = grids
    n, fi = best_nf_grid(jnp.asarray(E), jnp.asarray(T), OBJ_CARBON, carbon_intensity=0.0)
    assert int(n) == 1 and int(fi) == 0


def test_best_nf_grid_cost_matches_energy_when_price_positive(grids):
    T, _, E = grids
    n_c, f_c = best_nf_grid(jnp.asarray(E), jnp.asarray(T), OBJ_COST, price_kwh=0.2)
    n_e, f_e = best_nf_grid(jnp.asarray(E), jnp.asarray(T), OBJ_ENERGY)
    assert int(n_c) == int(n_e) and int(f_c) == int(f_e)


def test_best_nf_grid_deadline_filter(grids):
    T, _, E = grids
    ddl = 0.01  # excludes slow candidates
    n, fi = best_nf_grid(jnp.asarray(E), jnp.asarray(T), OBJ_ENERGY, deadline_s=ddl)
    best = brute_best_nf(8, lambda T, P: T * P, deadline=ddl)
    assert best is not None
    assert int(n) == best[1]
    assert float(FREQS[int(fi)]) == pytest.approx(best[2])


def test_best_nf_grid_deadline_infeasible_fallback(grids):
    T, _, E = grids
    n, fi = best_nf_grid(jnp.asarray(E), jnp.asarray(T), OBJ_ENERGY, deadline_s=1e-9)
    assert int(n) == 1 and int(fi) == len(FREQS) - 1  # reference fallback (1, f_max)


def test_min_n_for_sla():
    # find smallest n with size * T(n, f) * 1000 <= sla
    size, f, sla = 100.0, 1.0, 800.0
    got = int(min_n_for_sla(size, f, TC, sla, 8))
    want = next(
        (n for n in range(1, 9) if size * brute_T(n, f) * 1000.0 <= sla), 8
    )
    assert got == want


def test_min_n_for_sla_fallback_nmax():
    assert int(min_n_for_sla(1e9, 0.3, TC, 1.0, 8)) == 8

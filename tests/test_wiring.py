"""Round-2 wiring tests: every CLI flag reaches the component it configures.

VERDICT.md round 1 found parsed-but-dead flags (--rollouts,
--power-cap-constraint), an unreachable float64 time path, and a
crash-resume CSV duplication bug.  These tests pin the fixes:

* `--power-cap-constraint` sets the CMDP power target independently of
  `--power-cap` (reference wires them separately, run_sim_paper.py:107-114);
* `--time-dtype auto` promotes the simulated clock to float64 for
  long-horizon runs (f32 ulp at t=6e5 s is ~0.06 s, coarser than the ~9 ms
  inference service time, configs/paper.py);
* `--rollouts N` drives the mesh-sharded DistributedTrainer end-to-end from
  the CLI, streaming rollout 0's CSVs;
* resumed runs truncate CSVs to the checkpoint byte watermark, so re-run
  chunks don't append duplicate rows;
* the fused multi-update path (CHSAC_AF.train_steps) executes the same
  updates-per-experience schedule as the per-step loop, in one program.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import run_sim
from distributed_cluster_gpus_tpu.models import SimParams
from distributed_cluster_gpus_tpu.rl.cmdp import constraints_from_params


# ---------------------------------------------------------------------------
# --power-cap-constraint
# ---------------------------------------------------------------------------

class TestPowerCapConstraint:
    def test_defaults_to_power_cap(self):
        p = SimParams(algo="chsac_af", power_cap=5000.0)
        cs = {c.name: c.target for c in constraints_from_params(p)}
        assert cs["power"] == 5000.0

    def test_overrides_power_cap(self):
        p = SimParams(algo="chsac_af", power_cap=5000.0,
                      power_cap_constraint=3000.0)
        cs = {c.name: c.target for c in constraints_from_params(p)}
        assert cs["power"] == 3000.0  # CMDP target differs from the cap

    def test_unset_means_unconstrained(self):
        p = SimParams(algo="chsac_af")
        cs = {c.name: c.target for c in constraints_from_params(p)}
        assert cs["power"] >= 1e29

    def test_cli_reaches_params(self):
        a = run_sim.parse_args(["--algo", "chsac_af", "--power-cap", "5000",
                                "--power-cap-constraint", "3000"])
        params = run_sim.build_params(a)
        assert params.power_cap == 5000.0
        assert params.power_cap_constraint == 3000.0


# ---------------------------------------------------------------------------
# --time-dtype
# ---------------------------------------------------------------------------

class TestTimeDtype:
    def test_auto_promotes_long_runs(self):
        a = run_sim.parse_args(["--duration", "604800"])
        assert run_sim.resolve_time_dtype(a) == "float64"

    def test_auto_keeps_f32_short_runs(self):
        a = run_sim.parse_args(["--duration", "3600"])
        assert run_sim.resolve_time_dtype(a) == "float32"

    def test_explicit_wins(self):
        a = run_sim.parse_args(["--duration", "604800", "--time-dtype", "float32"])
        assert run_sim.resolve_time_dtype(a) == "float32"

    def test_long_horizon_latency_resolution(self, single_dc_fleet):
        """At t~6e5 s the f64 clock must keep ms-scale sojourn resolution.

        Warm-starts the state clock near the end of the reference's canonical
        7-day run (`/root/reference/run.sh:21-24`, duration 604800) and
        checks emitted inference latencies still carry sub-f32-ulp detail
        (the f32 ulp at 6e5 is 1/16 s; service times are ~9 ms).
        """
        from jax.experimental import enable_x64
        from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

        # jax 0.4.37 ships the context manager under jax.experimental only
        # (the jax.enable_x64 alias these tests used was removed upstream)
        with enable_x64(True):
            params = SimParams(algo="default_policy", duration=604800.0,
                               log_interval=20.0, inf_mode="poisson",
                               inf_rate=4.0, trn_mode="off", job_cap=64,
                               seed=3, time_dtype="float64")
            engine = Engine(single_dc_fleet, params)
            state = init_state(jax.random.key(3), single_dc_fleet, params)
            t0 = 604500.0
            state = state.replace(
                t=jnp.asarray(t0, jnp.float64),
                next_arrival=jnp.full_like(state.next_arrival, jnp.inf).at[0, 0].set(t0 + 0.5),
                next_log_t=jnp.asarray(t0 + 20.0, jnp.float64),
            )
            assert state.t.dtype == jnp.float64
            state, em = engine.run_chunk(state, None, n_steps=256)
            valid = np.asarray(em["job_valid"])
            assert valid.any(), "no jobs finished in the probe window"
            rows = np.asarray(em["job"])[valid]
            lat = rows[:, 10]  # latency_s column
            # f32 time would quantize start/finish to 1/16 s at t=6e5 —
            # every latency would be a multiple of 0.0625.  f64 keeps ms.
            frac = np.abs(lat / 0.0625 - np.round(lat / 0.0625))
            assert (frac > 1e-3).any(), (
                f"latencies quantized to f32 ulp grid: {lat[:8]}")

    def test_chsac_replay_ingest_under_x64(self, single_dc_fleet):
        """f64-clock chsac must ingest into the replay ring.  Regression:
        the canonical week run crashed at the first ingest because the
        slot-ring's Python-literal zero indices promoted to int64 under
        jax_enable_x64 while the ring pointer stayed int32
        (dynamic_update_slice requires one uniform index type)."""
        from jax.experimental import enable_x64
        from distributed_cluster_gpus_tpu.rl.train import make_agent
        from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state

        with enable_x64(True):  # jax.enable_x64 removed upstream, see above
            params = SimParams(algo="chsac_af", duration=604800.0,
                               log_interval=20.0, inf_mode="poisson",
                               inf_rate=4.0, trn_mode="off", job_cap=64,
                               lat_window=64, rl_warmup=8, rl_batch=8,
                               seed=3, time_dtype="float64")
            agent = make_agent(single_dc_fleet, params)
            engine = Engine(single_dc_fleet, params,
                            policy_apply=agent.policy_apply)
            state = init_state(jax.random.key(3), single_dc_fleet, params)
            state, em = engine.run_chunk(state, agent.sac, n_steps=512)
            agent.ingest_chunk(em["rl"])  # crashed pre-fix
            assert int(agent.replay.n_seen) > 0


# ---------------------------------------------------------------------------
# --rollouts N end-to-end through the CLI
# ---------------------------------------------------------------------------

class TestRolloutsCLI:
    def test_distributed_cli_writes_csvs(self, tmp_path):
        out = str(tmp_path / "out")
        run_sim.main([
            "--algo", "chsac_af", "--rollouts", "8", "--duration", "60",
            "--log-interval", "10", "--single-dc", "--job-cap", "64",
            "--chunk-steps", "64", "--rl-warmup", "32", "--rl-batch", "32",
            "--inf-mode", "poisson", "--inf-rate", "4.0",
            "--trn-mode", "poisson", "--trn-rate", "0.1",
            "--out", out, "--quiet",
        ])
        cluster = (tmp_path / "out" / "cluster_log.csv").read_text().splitlines()
        job = (tmp_path / "out" / "job_log.csv").read_text().splitlines()
        assert len(cluster) > 1 and len(job) > 1
        # rollout-0 stream: times are monotone non-decreasing within the file
        times = [float(r.split(",")[0]) for r in cluster[1:]]
        assert times == sorted(times)
        # jid column unique (no duplicated rows from multiple rollouts)
        jids = [r.split(",")[0] for r in job[1:]]
        assert len(jids) == len(set(jids))


class TestPPOCLI:
    def test_ppo_cli_writes_csvs(self, tmp_path):
        """--algo ppo: mesh-sharded on-policy training with rollout-0 CSV
        streaming, end to end through the CLI."""
        out = str(tmp_path / "ppo")
        run_sim.main([
            "--algo", "ppo", "--rollouts", "8", "--duration", "40",
            "--log-interval", "10", "--single-dc", "--job-cap", "64",
            "--chunk-steps", "64",
            "--inf-mode", "poisson", "--inf-rate", "4.0", "--trn-mode", "off",
            "--out", out, "--quiet",
        ])
        cluster = (tmp_path / "ppo" / "cluster_log.csv").read_text().splitlines()
        job = (tmp_path / "ppo" / "job_log.csv").read_text().splitlines()
        assert len(cluster) > 1 and len(job) > 1
        times = [float(r.split(",")[0]) for r in cluster[1:]]
        assert times == sorted(times)


class TestOfflineDatasetCLI:
    def test_offline_pretrain_e2e(self, tmp_path, capsys):
        """run -> build npz (module CLI) -> --offline-dataset pretrain ->
        online run: the full offline-RL path through the public entry
        points."""
        from distributed_cluster_gpus_tpu.rl import offline

        src = str(tmp_path / "src")
        run_sim.main([
            "--algo", "joint_nf", "--duration", "40", "--log-interval", "10",
            "--single-dc", "--job-cap", "64", "--chunk-steps", "512",
            "--inf-mode", "poisson", "--inf-rate", "3.0", "--trn-mode", "off",
            "--out", src, "--quiet",
        ])
        npz = str(tmp_path / "ds.npz")
        offline._main([src, npz, "--single-dc"])
        assert "wrote" in capsys.readouterr().out

        out = str(tmp_path / "warm")
        run_sim.main([
            "--algo", "chsac_af", "--duration", "30", "--log-interval", "10",
            "--single-dc", "--job-cap", "64", "--chunk-steps", "256",
            "--rl-warmup", "16", "--rl-batch", "8",
            "--offline-dataset", npz, "--offline-steps", "6",
            "--inf-mode", "poisson", "--inf-rate", "3.0", "--trn-mode", "off",
            "--out", out, "--quiet",
        ])
        job = (tmp_path / "warm" / "job_log.csv").read_text().splitlines()
        assert len(job) > 1  # pretrained agent ran the online sim to the end


# ---------------------------------------------------------------------------
# Workload realization is algorithm-independent
# ---------------------------------------------------------------------------

class TestSameWorkloadAcrossAlgos:
    def test_arrival_streams_identical(self, single_dc_fleet, tmp_path):
        """Arrival gaps + job sizes come from a dedicated per-stream PRNG
        chain, so two algorithms with different event interleavings see the
        bit-identical workload (jid -> (ingress, type, size) matches)."""
        import pandas as pd

        from distributed_cluster_gpus_tpu.sim.io import run_simulation

        frames = {}
        for algo in ("default_policy", "joint_nf"):
            params = SimParams(algo=algo, duration=120.0, log_interval=20.0,
                               inf_mode="poisson", inf_rate=4.0,
                               trn_mode="poisson", trn_rate=0.2,
                               job_cap=128, seed=11)
            out = str(tmp_path / algo)
            run_simulation(single_dc_fleet, params, out_dir=out,
                           chunk_steps=512)
            frames[algo] = pd.read_csv(out + "/job_log.csv").set_index("jid")
        a, b = frames["default_policy"], frames["joint_nf"]
        common = a.index.intersection(b.index)
        assert len(common) > 50
        for col in ("ingress", "type", "size"):
            assert (a.loc[common, col] == b.loc[common, col]).all(), col


# ---------------------------------------------------------------------------
# CSV byte watermark (crash-resume dedup)
# ---------------------------------------------------------------------------

class TestCSVWatermark:
    def test_truncate_to_restores_prefix(self, tmp_path, single_dc_fleet):
        from distributed_cluster_gpus_tpu.sim.io import CSVWriters

        w = CSVWriters(str(tmp_path), single_dc_fleet)
        row = np.asarray([[1.0] * 14], np.float32)
        w.write_cluster_chunk(row[None], [0])
        wm = w.offsets()
        before = open(w.cluster_path, "rb").read()
        # a "crashed" run appends more rows past the checkpoint
        w.write_cluster_chunk(row[None], [0])
        w.write_cluster_chunk(row[None], [0])
        assert os.path.getsize(w.cluster_path) > wm["cluster"]
        # resume truncates back to the watermark
        w2 = CSVWriters(str(tmp_path), single_dc_fleet, append=True)
        w2.truncate_to(wm)
        assert open(w.cluster_path, "rb").read() == before


# ---------------------------------------------------------------------------
# Fused multi-step SAC updates
# ---------------------------------------------------------------------------

class TestFusedTrainSteps:
    @pytest.fixture()
    def agent(self):
        from distributed_cluster_gpus_tpu.rl.agent import CHSAC_AF
        from distributed_cluster_gpus_tpu.rl.cmdp import N_COSTS

        ag = CHSAC_AF(obs_dim=13, n_dc=2, n_g_choices=4, batch=8,
                      buffer_capacity=256, warmup=16, seed=0)
        n = 32
        tr = {
            "valid": jnp.ones((n,), bool),
            "s0": jnp.ones((n, 13), jnp.float32),
            "s1": jnp.zeros((n, 13), jnp.float32),
            "a_dc": jnp.zeros((n,), jnp.int32),
            "a_g": jnp.zeros((n,), jnp.int32),
            "r": jnp.ones((n,), jnp.float32),
            "costs": jnp.zeros((n, N_COSTS), jnp.float32),
            "mask_dc": jnp.ones((n, 2), bool),
            "mask_g": jnp.ones((n, 4), bool),
        }
        ag.ingest_chunk(tr)
        return ag

    def test_runs_requested_updates(self, agent):
        m, n_done = agent.train_steps(5, max_steps=8)
        assert n_done == 5
        assert int(agent.sac.step) == 5
        assert m is not None and np.isfinite(float(m["critic_loss"]))

    def test_caps_at_max(self, agent):
        _, n_done = agent.train_steps(100, max_steps=8)
        assert n_done == 8

    def test_warmup_gates_to_zero(self):
        from distributed_cluster_gpus_tpu.rl.agent import CHSAC_AF

        ag = CHSAC_AF(obs_dim=13, n_dc=2, n_g_choices=4, batch=8,
                      buffer_capacity=256, warmup=1_000, seed=0)
        m, n_done = ag.train_steps(5, max_steps=8)
        assert n_done == 0 and m is None
        assert int(ag.sac.step) == 0


class TestRouterWeightsCLI:
    def test_latency_only_weights_route_to_nearest_dc(self, tmp_path, fleet):
        """--router-weights 1,0,0,0,0 scores DCs by network latency alone,
        so every arrival must land at its ingress's min-latency DC — the
        routing heatmap collapses to one column per ingress (vs uniform-
        random under the default)."""
        import pandas as pd

        out = str(tmp_path / "wout")
        run_sim.main([
            "--algo", "default_policy", "--duration", "60",
            "--log-interval", "10", "--router-weights", "1,0,0,0,0",
            "--inf-mode", "poisson", "--inf-rate", "6.0",
            "--trn-mode", "off", "--job-cap", "256",
            "--chunk-steps", "512", "--out", out, "--quiet",
        ])
        jb = pd.read_csv(out + "/job_log.csv")
        assert len(jb) > 100
        ing_idx = {n: i for i, n in enumerate(fleet.ingress_names)}
        dc_idx = {n: i for i, n in enumerate(fleet.dc_names)}
        net = np.asarray(fleet.net_lat_s)
        for ing_name, grp in jb.groupby("ingress"):
            want = int(np.argmin(net[ing_idx[ing_name]]))
            got = {dc_idx[d] for d in grp["dc"].unique()}
            assert got == {want}, (ing_name, got, want)

    def test_queue_weight_spreads_load(self, tmp_path):
        """A queue-dominated weight vector must route to more than one DC
        (pure-latency routing saturates the nearest DC; the queue term
        pushes overflow elsewhere)."""
        import pandas as pd

        out = str(tmp_path / "qout")
        run_sim.main([
            "--algo", "default_policy", "--duration", "60",
            "--log-interval", "10", "--router-weights", "1,0,0,0,1000",
            "--inf-mode", "poisson", "--inf-rate", "20.0",
            "--trn-mode", "off", "--job-cap", "512",
            "--chunk-steps", "512", "--out", out, "--quiet",
        ])
        jb = pd.read_csv(out + "/job_log.csv")
        assert jb["dc"].nunique() > 1

    def test_bad_weight_count_rejected(self):
        with pytest.raises(ValueError, match="exactly 5"):
            SimParams(algo="default_policy", router_weights=(1.0, 2.0))


def test_rl_energy_weight_flag_wiring():
    """--rl-energy-weight reaches SimParams; default 1.0 is the reference
    reward (r = -E_unit + 0.05/n, `simulator_paper_multi.py:764-774`)."""
    a = run_sim.parse_args(["--algo", "chsac_af", "--duration", "10"])
    assert run_sim.build_params(a).rl_energy_weight == 1.0
    a = run_sim.parse_args(["--algo", "chsac_af", "--duration", "10",
                            "--rl-energy-weight", "16"])
    assert run_sim.build_params(a).rl_energy_weight == 16.0

"""Closed-form checks of the physics chain (exactly checkable, SURVEY §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.ops.physics import (
    LatencyCoeffs,
    PowerCoeffs,
    baseline_dc_power_w,
    energy_tuple,
    gpu_power_w,
    idle_power_w,
    step_time_s,
    task_power_w,
)

PC = PowerCoeffs(jnp.float32(75.0), jnp.float32(80.0), jnp.float32(110.0))
TC = LatencyCoeffs(jnp.float32(0.0045), jnp.float32(0.032), jnp.float32(0.0012))


def test_gpu_power_closed_form():
    for f in (0.3, 0.7, 1.0):
        expected = 75.0 * f**3 + 80.0 * f + 110.0
        assert float(gpu_power_w(f, PC)) == pytest.approx(expected, rel=1e-6)


def test_gpu_power_clamps_negative_freq():
    assert float(gpu_power_w(-1.0, PC)) == pytest.approx(110.0)


def test_task_power_scales_linearly_and_clamps_n():
    p1 = float(gpu_power_w(0.8, PC))
    assert float(task_power_w(4, 0.8, PC)) == pytest.approx(4 * p1, rel=1e-6)
    assert float(task_power_w(-3, 0.8, PC)) == 0.0


def test_step_time_piecewise_n1():
    # n == 1: no gamma_t * n sync penalty
    for f in (0.3, 1.0):
        assert float(step_time_s(1, f, TC)) == pytest.approx(0.0045 + 0.032 / f, rel=1e-6)


def test_step_time_piecewise_n_gt_1():
    for n in (2, 8):
        for f in (0.4, 1.0):
            expected = (0.0045 + 0.032 / f + 0.0012 * n) / n
            assert float(step_time_s(n, f, TC)) == pytest.approx(expected, rel=1e-6)


def test_step_time_clamps():
    assert float(step_time_s(0, 1.0, TC)) == float(step_time_s(1, 1.0, TC))
    assert np.isfinite(float(step_time_s(1, 0.0, TC)))


def test_energy_tuple_consistency():
    T, P, E = energy_tuple(4, 0.7, PC, TC)
    assert float(E) == pytest.approx(float(T) * float(P), rel=1e-6)


def test_broadcasting_over_grid():
    n = jnp.arange(1, 9)[:, None]
    f = jnp.asarray([0.3, 0.6, 1.0])[None, :]
    T = step_time_s(n, f, TC)
    assert T.shape == (8, 3)
    assert float(T[0, 2]) == pytest.approx(0.0045 + 0.032, rel=1e-6)


def test_idle_and_baseline_power():
    assert float(idle_power_w(10, 45.0, 28.0, True)) == pytest.approx(280.0)
    assert float(idle_power_w(10, 45.0, 28.0, False)) == pytest.approx(450.0)
    # 2 busy at f=1.0: 2*(45+350) + 14 idle sleeping: 14*28
    p = baseline_dc_power_w(2, 16, 1.0, 45.0, 350.0, 28.0, 3.0, True)
    assert float(p) == pytest.approx(2 * 395.0 + 14 * 28.0, rel=1e-6)

"""Population-based chaos training (rl/population.py).

Quick tier: manifest commit/restore through the verified store (incl.
crash injection and corrupt-newest fallback), the leaderboard score,
deterministic member draws, population-aware fsck/gc recursion, the
``replay_abort --member`` bundle resolver, winner fall-through, and the
member-labeled health gates.  Slow tier: the fault-isolation e2e (one
member forced to diverge; the untouched members are byte-identical to a
no-fault run), manifest resume after a mid-interval crash, the
corrupt-store cull-and-replace path, and the N=1 degeneracy to the
serial campaign.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from distributed_cluster_gpus_tpu.configs.paper import build_duo_fleet
from distributed_cluster_gpus_tpu.fault import ChaosCurriculum
from distributed_cluster_gpus_tpu.fault.curriculum import ramp_stages
from distributed_cluster_gpus_tpu.models import FaultParams, SimParams
from distributed_cluster_gpus_tpu.obs.health import (DivergenceError,
                                                     Watchdog, WatchdogError)
from distributed_cluster_gpus_tpu.rl.campaign import DivergenceMonitor
from distributed_cluster_gpus_tpu.rl.population import (
    MANIFEST_SCHEMA, POPULATION_SUMMARY_FILE, PopulationConfig,
    PopulationError, _draw_hyper, _member_seed, evaluate_population,
    leaderboard_winner_ckpt, load_population_manifest, locate_member_bundle,
    run_population, save_population_manifest)
from distributed_cluster_gpus_tpu.utils.checkpoint import (
    CheckpointCrashInjected, gc_checkpoints, gc_population,
    is_population_root, population_member_stores, save_checkpoint,
    step_dirname, steps)


@pytest.fixture(scope="module")
def duo_fleet():
    return build_duo_fleet()


TINY_CUR = ChaosCurriculum(
    name="tiny", mtbf_lo_s=40.0, mtbf_hi_s=120.0,
    mttr_lo_s=10.0, mttr_hi_s=25.0).sized_for(60.0)

CHSAC_KW = dict(
    algo="chsac_af", duration=30.0, log_interval=5.0,
    inf_mode="poisson", inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
    job_cap=128, queue_cap=256, seed=11, rl_warmup=64, rl_batch=32,
)

#: short held-out eval so the leaderboard barrier stays CI-affordable
POP_CFG_KW = dict(eval_duration=30.0, eval_chunk_steps=256,
                  eval_max_chunks=16)


def chaos_params(**over):
    kw = dict(CHSAC_KW, faults=FaultParams(curriculum=TINY_CUR),
              obs_enabled=True)
    kw.update(over)
    return SimParams(**kw)


def _corrupt_first_payload(step_dir):
    man = json.load(open(os.path.join(step_dir, "manifest.json")))
    victim = os.path.join(step_dir, sorted(man["files"])[0])
    with open(victim, "r+b") as f:
        b0 = f.read(1)
        f.seek(0)
        f.write(bytes([b0[0] ^ 0xFF]))


# ---------------------------------------------------------------------------
# config + member draws (quick)
# ---------------------------------------------------------------------------

def test_population_config_validated():
    with pytest.raises(ValueError, match="n_members"):
        PopulationConfig(n_members=0)
    with pytest.raises(ValueError, match="member_retries"):
        PopulationConfig(member_retries=-1)
    with pytest.raises(ValueError, match="exploit_quantile"):
        PopulationConfig(exploit_quantile=1.0)
    with pytest.raises(ValueError, match="perturb_scale"):
        PopulationConfig(perturb_scale=-0.1)


def test_member_draws_deterministic():
    """Member seeds and hyper jitters are pure functions of (base seed,
    slot, generation) — no member's draw can depend on another member's
    fate, which is what the byte-isolation e2e relies on."""
    assert _member_seed(11, 0) == 11, \
        "member 0 must inherit the base seed (campaign degeneracy)"
    assert _member_seed(11, 1) != _member_seed(11, 2)
    assert _member_seed(11, 1, generation=1) != _member_seed(11, 1)
    base = {"lr": 3e-4, "alpha_init": 0.2}
    # identity draws: member 0 at init, any member at scale 0
    assert _draw_hyper(base, 11, 0, 0.3) == base
    assert _draw_hyper(base, 11, 3, 0.0) == base
    h1 = _draw_hyper(base, 11, 3, 0.3)
    assert h1 == _draw_hyper(base, 11, 3, 0.3)
    assert h1 != base and h1["lr"] > 0 and h1["alpha_init"] > 0
    assert _draw_hyper(base, 11, 0, 0.3, salt=5) != base, \
        "explore-time draws (salt>0) must perturb member 0 too"


def test_chaos_score_directions():
    from distributed_cluster_gpus_tpu.evaluation import chaos_score

    base = {"availability": 0.9, "migration_success_rate": 0.5,
            "completed_inf": 100, "completed_trn": 10, "dropped": 5,
            "energy_kwh": 2.0, "energy_cost_usd": 1.0, "carbon_kg": 1.0}
    s0 = chaos_score(base)
    assert chaos_score({**base, "availability": 0.95}) > s0
    assert chaos_score({**base, "migration_success_rate": 0.9}) > s0
    assert chaos_score({**base, "energy_kwh": 4.0}) < s0
    assert chaos_score({**base, "dropped": 50}) < s0
    # NaN migration (nothing preempted) scores as 0, not NaN
    nan_row = {**base, "migration_success_rate": float("nan")}
    assert np.isfinite(chaos_score(nan_row))


def test_health_gates_carry_member_label():
    w = Watchdog(mode="raise", member=3, log=lambda m: None)
    with pytest.raises(WatchdogError) as ei:
        w.check(np.asarray([1, 0, 0, 0, 0, 0, 0]))
    assert ei.value.member == 3
    assert "member 3" in str(ei.value)
    m = DivergenceMonitor(member=5)
    with pytest.raises(DivergenceError) as ei:
        m.check(2, {"critic_loss": float("nan")})
    assert ei.value.member == 5
    assert "member 5" in str(ei.value)


# ---------------------------------------------------------------------------
# manifest store (quick; numpy payloads only — crash-injection like PR 10)
# ---------------------------------------------------------------------------

def _manifest_doc(next_stage, tag):
    return {"schema": MANIFEST_SCHEMA, "schema_version": 1,
            "curriculum": "tiny", "n_stages": 2, "n_members": 2,
            "next_stage": next_stage, "next_reseed": 2000 + next_stage,
            "members": [{"member": 0, "tag": tag}], "quarantine": [],
            "intervals": []}


def test_manifest_commit_restore_roundtrip(tmp_path):
    td = str(tmp_path)
    save_population_manifest(td, 0, _manifest_doc(0, "init"))
    save_population_manifest(td, 1, _manifest_doc(1, "after0"))
    step, doc = load_population_manifest(td)
    assert (step, doc["next_stage"]) == (1, 1)
    assert doc["members"][0]["tag"] == "after0"
    # the human-readable mirror matches the committed doc
    mirror = json.load(open(os.path.join(td, "population_manifest.json")))
    assert mirror == doc
    assert is_population_root(td)


def test_manifest_crash_injection_falls_back(tmp_path, monkeypatch):
    """A crash at ANY phase of the interval-1 commit leaves the
    interval-0 manifest restorable — the SIGKILL-mid-PBT-interval resume
    guarantee, driven through the PR-10 injection hooks."""
    td = str(tmp_path)
    save_population_manifest(td, 0, _manifest_doc(0, "init"))
    for point in ("staged", "manifest", "marker"):
        monkeypatch.setenv("DCG_CKPT_CRASH_POINT", point)
        with pytest.raises(CheckpointCrashInjected):
            save_population_manifest(td, 1, _manifest_doc(1, "torn"))
        monkeypatch.delenv("DCG_CKPT_CRASH_POINT")
        step, doc = load_population_manifest(td)
        assert (step, doc["members"][0]["tag"]) == (0, "init"), \
            f"crash at {point!r} must leave interval 0 authoritative"
        # sweep the stranded staging debris before the next attempt
        gc_checkpoints(os.path.join(td, "manifest_store"))
    save_population_manifest(td, 1, _manifest_doc(1, "after0"))
    assert load_population_manifest(td)[0] == 1


def test_manifest_corrupt_newest_falls_back(tmp_path):
    td = str(tmp_path)
    save_population_manifest(td, 0, _manifest_doc(0, "init"))
    save_population_manifest(td, 1, _manifest_doc(1, "after0"))
    store = os.path.join(td, "manifest_store")
    _corrupt_first_payload(os.path.join(store, step_dirname(1)))
    step, doc = load_population_manifest(td)
    assert (step, doc["members"][0]["tag"]) == (0, "init")


# ---------------------------------------------------------------------------
# population-aware fsck / gc / bundle resolution (quick; fixture stores)
# ---------------------------------------------------------------------------

def _fixture_population(td, corrupt_member=None):
    """Minimal on-disk population: manifest + 2 members x 1 segment store
    (numpy payloads), optional bit rot on one member's newest step."""
    trees = {"x": np.arange(8)}
    doc = _manifest_doc(2, "final")
    doc["members"] = []
    for k in range(2):
        store = os.path.join(td, f"member_{k:02d}", "ck", "stage00_try00")
        save_checkpoint(store, 0, **trees)
        save_checkpoint(store, 1, **trees)
        doc["members"].append({
            "member": k, "generation": 0, "seed": 11 + k,
            "reseed": 1000 * k, "hyper": None, "status": "active",
            "retries_left": 2, "attempts": 1,
            "ckpt_dirs": [os.path.join(f"member_{k:02d}", "ck",
                                       "stage00_try00")],
            "history": [], "lineage": [], "score": float(k),
            "metrics": None})
    doc["leaderboard"] = [
        {"rank": 0, "member": 1, "score": 1.0},
        {"rank": 1, "member": 0, "score": 0.0}]
    save_population_manifest(td, 0, doc)
    from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

    dump_json_atomic(os.path.join(td, POPULATION_SUMMARY_FILE), doc)
    if corrupt_member is not None:
        store = os.path.join(td, f"member_{corrupt_member:02d}", "ck",
                             "stage00_try00")
        for s in steps(store):
            _corrupt_first_payload(os.path.join(store, step_dirname(s)))
    return doc


def test_population_member_stores_and_gc(tmp_path):
    td = str(tmp_path)
    _fixture_population(td)
    stores = population_member_stores(td)
    assert [m for m, _ in stores] == ["member_00", "member_01"]
    # strand staging debris in one member store + the manifest store
    debris = os.path.join(stores[0][1], "step_0000000009_tmp")
    os.makedirs(debris)
    man_debris = os.path.join(td, "manifest_store", "step_0000000009_tmp")
    os.makedirs(man_debris)
    reports = gc_population(td, keep=1)
    assert not os.path.isdir(debris) and not os.path.isdir(man_debris)
    # retention pruned each member store to its newest verified step,
    # but never the manifest store (older intervals are the resume chain)
    for _m, store in stores:
        assert steps(store) == [1]
    assert steps(os.path.join(td, "manifest_store")) == [0]
    assert set(reports) == {stores[0][1], stores[1][1],
                            os.path.join(td, "manifest_store")}
    # gc_checkpoints(recurse=True) reaches the same stores from the root
    debris2 = os.path.join(stores[1][1], "step_0000000008_tmp")
    os.makedirs(debris2)
    rep = gc_checkpoints(td, recurse=True)
    assert not os.path.isdir(debris2)
    assert any("step_0000000008_tmp" in s for s in rep["swept"])


def test_fsck_population_detects_corrupt_member(tmp_path, capsys):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import fsck_ckpt

    td = str(tmp_path)
    _fixture_population(td)
    assert fsck_ckpt.main([td]) == 0
    out = capsys.readouterr().out
    assert "member_00" in out and "member_01" in out
    assert "manifest_store" in out
    # bit-rot the newest step of member 1's store: fsck must FAIL and
    # name the digest mismatch
    store = os.path.join(td, "member_01", "ck", "stage00_try00")
    _corrupt_first_payload(os.path.join(store, step_dirname(1)))
    assert fsck_ckpt.main([td]) == 1
    err = capsys.readouterr().err
    assert "digest mismatch" in err


def test_locate_member_bundle(tmp_path):
    from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

    td = str(tmp_path)
    doc = _fixture_population(td)
    with pytest.raises(PopulationError, match="no forensic abort bundle"):
        locate_member_bundle(td, 0)
    # a quarantine bundle for member 0 (context + forensic step)
    bundle = os.path.join(td, "member_00", "ck", "stage01_try01", "aborted")
    save_checkpoint(bundle, 3, x=np.arange(4))
    dump_json_atomic(os.path.join(bundle, "abort_context.json"),
                     {"schema": "dcg.abort_context.v1", "kind": "divergence",
                      "chunk": 3, "probes": ["critic_loss_max"]})
    # scan route (no quarantine log entry yet)
    assert locate_member_bundle(td, 0) == bundle
    # quarantine-log route wins and is exact
    doc["quarantine"] = [{"member": 0, "stage": 1, "attempt": 1,
                          "bundle": os.path.join("member_00", "ck",
                                                 "stage01_try01", "aborted")}]
    dump_json_atomic(os.path.join(td, POPULATION_SUMMARY_FILE), doc)
    assert locate_member_bundle(td, 0) == bundle
    with pytest.raises(PopulationError):
        locate_member_bundle(td, 1)


def test_replay_abort_member_flag_resolves(tmp_path, capsys):
    """--member resolves the bundle inside a population root and then
    fails exactly like a direct path on an incomplete bundle (the full
    replay e2e is covered by test_replay.py on single-learner bundles —
    the resolver is the only new moving part)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import replay_abort

    td = str(tmp_path)
    _fixture_population(td)
    rc = replay_abort.main([td, "--member", "0"])
    assert rc == 2  # resolver found nothing: member never quarantined
    err = capsys.readouterr().err
    assert "no forensic abort bundle" in err


def test_leaderboard_winner_falls_past_corrupt_store(tmp_path):
    td = str(tmp_path)
    _fixture_population(td, corrupt_member=1)  # member 1 ranks first
    lines = []
    src, step, member = leaderboard_winner_ckpt(td, log=lines.append)
    assert member == 0, "corrupt winner store must fall through to rank 2"
    assert step == 1 and src.endswith(os.path.join("member_00", "ck",
                                                   "stage00_try00"))
    assert any("no verified checkpoint" in ln for ln in lines)
    assert any("warm-ckpt donor" in ln for ln in lines)


def test_chaos_sweep_accepts_population_root(tmp_path, monkeypatch, capsys):
    """--warm-ckpt POP_ROOT resolves to the winner's store before any
    cell runs (the sweep itself is covered by test_chaos.py)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import chaos_sweep

    td = str(tmp_path)
    _fixture_population(td)
    out_json = os.path.join(td, "sweep.json")
    # no algos -> the sweep resolves --warm-ckpt, runs zero cells, saves
    chaos_sweep.main(["--warm-ckpt", td, "--algos", "", "--tiny",
                      "--json", out_json, "--rates", "0"])
    out = capsys.readouterr().out
    assert "population root" in out and "member 1" in out
    assert os.path.exists(out_json)


def test_run_sim_population_flag_validation():
    import run_sim

    with pytest.raises(SystemExit, match="requires --algo chsac_af"):
        run_sim.main(["--population", "2", "--algo", "ppo"])
    with pytest.raises(SystemExit, match="mutually exclusive"):
        run_sim.main(["--population", "2", "--campaign",
                      "--algo", "chsac_af"])
    with pytest.raises(SystemExit, match="--obs-watchdog off"):
        run_sim.main(["--population", "2", "--algo", "chsac_af",
                      "--obs", "--obs-watchdog", "off"])


# ---------------------------------------------------------------------------
# e2e (slow tier)
# ---------------------------------------------------------------------------

class TripMemberOnce(DivergenceMonitor):
    """Forced divergence: trips once, on the very first chunk check
    (the 30 s segments complete in one chunk, so waiting for chunk 1
    would never fire)."""

    def __init__(self, member=None):
        super().__init__(member=member)
        self.armed = True

    def check(self, chunk, metrics):
        if self.armed:
            self.armed = False
            self._trip(chunk, "forced test divergence")


def test_population_fault_isolation_and_leaderboard_e2e(duo_fleet, tmp_path):
    """The acceptance loop: an N=4 population with one member forced to
    diverge completes — the tripping member is quarantined (forensic
    bundle on disk), rolled back, and retried, while the other three
    members' training is BYTE-identical to a no-fault run of the same
    seeds; the leaderboard reproduces from the stored checkpoints."""
    td = str(tmp_path)
    cfg = PopulationConfig(n_members=4, member_retries=1,
                           exploit_quantile=0.0, **POP_CFG_KW)
    pop_faulty = os.path.join(td, "faulty")
    agents_f, report = run_population(
        duo_fleet, chaos_params(), out_dir=pop_faulty, chunk_steps=512,
        config=cfg, monitors={0: TripMemberOnce(member=0)})
    assert report["status"] == "completed"
    assert len(report["quarantine"]) == 1
    q = report["quarantine"][0]
    assert (q["member"], q["kind"], q["action"]) == (0, "divergence",
                                                     "rolled_back") \
        or (q["member"], q["action"]) == (0, "restarted")
    # the forensic bundle is real PR-10 machinery: context + checkpoint
    assert q["bundle"] is not None
    bundle = os.path.join(pop_faulty, q["bundle"])
    ctx = json.load(open(os.path.join(bundle, "abort_context.json")))
    assert ctx["kind"] == "divergence"
    assert locate_member_bundle(pop_faulty, 0) == bundle
    # member 0 healed: an aborted then a completed attempt
    m0 = [r for r in report["members"] if r["member"] == 0][0]
    assert [h["outcome"] for h in m0["history"]] == ["aborted", "completed"]
    assert m0["history"][1]["reseed"] == m0["history"][0]["reseed"] + 1
    # fault isolation: members 1..3 byte-identical to a no-fault run
    pop_clean = os.path.join(td, "clean")
    agents_c, report_c = run_population(
        duo_fleet, chaos_params(), out_dir=pop_clean, chunk_steps=512,
        config=cfg)
    assert report_c["status"] == "completed"
    assert report_c["quarantine"] == []
    from conftest import tree_mismatches

    for k in (1, 2, 3):
        assert tree_mismatches(agents_f[k].sac, agents_c[k].sac) == [], \
            f"member {k} training must be byte-unaffected by member 0's " \
            "quarantine"
    # leaderboard: ranked, scored, and reproducible from the stored
    # checkpoints (pure function of seed + stored policy weights)
    lead = report["leaderboard"]
    assert len(lead) == 4
    assert [e["rank"] for e in lead] == [0, 1, 2, 3]
    scores = [e["score"] for e in lead]
    assert scores == sorted(scores, reverse=True)
    redo = evaluate_population(duo_fleet, chaos_params(), pop_faulty,
                               config=cfg)
    assert [e["member"] for e in redo] == [e["member"] for e in lead], \
        "re-running the held-out eval from the stored checkpoints must " \
        "reproduce the leaderboard ranking"
    for e_new, e_old in zip(redo, lead):
        assert e_new["score"] == pytest.approx(e_old["score"], abs=0.0), \
            "the policy-only graft must reproduce the exact scores"
    # population summary is strict JSON on disk
    doc = json.loads(open(os.path.join(
        pop_faulty, POPULATION_SUMMARY_FILE)).read(),
        parse_constant=lambda s: pytest.fail(f"non-strict JSON token {s}"))
    assert doc["schema"] == "dcg.population_summary.v1"
    assert doc["schema_version"] == 1


def test_population_resume_from_manifest(duo_fleet, tmp_path):
    """A driver killed mid-PBT-interval resumes from the last committed
    population_manifest.json to the IDENTICAL member table — including a
    weight graft recorded at that interval, which exists only in the
    manifest lineage until the member's next checkpoint — and completes
    BYTE-identically to an uninterrupted run of the same seeds."""
    td = str(tmp_path)
    cur = dataclasses.replace(TINY_CUR, stages=ramp_stages(2))
    params = chaos_params(faults=FaultParams(curriculum=cur))
    # exploit ON: interval 0 grafts the winner into the bottom member,
    # so the resume must re-apply the graft, not restore pre-graft
    cfg = PopulationConfig(n_members=2, member_retries=1,
                           exploit_quantile=0.5, **POP_CFG_KW)

    class CrashMidInterval(Exception):
        pass

    class CrashMonitor(DivergenceMonitor):
        """Simulated hard crash (NOT a RunAbort): unwinds the driver
        mid-interval at stage 1, after interval 0 committed."""

        def __init__(self):
            super().__init__()
            self.calls = 0

        def check(self, chunk, metrics):
            self.calls += 1
            if self.calls > 1:  # let stage 0 complete, die in stage 1
                raise CrashMidInterval("simulated SIGKILL")

    crash_dir = os.path.join(td, "crashed")
    with pytest.raises(CrashMidInterval):
        run_population(duo_fleet, params, out_dir=crash_dir,
                       chunk_steps=512, config=cfg,
                       monitors={1: CrashMonitor()})
    step, manifest = load_population_manifest(crash_dir)
    assert manifest["next_stage"] == 1, \
        "only interval 0 committed before the crash"
    assert manifest["intervals"][0]["grafts"], \
        "interval 0 must have exploited the winner into the bottom member"
    table_before = [(m["member"], m["seed"], m["reseed"], m["status"],
                     m["retries_left"]) for m in manifest["members"]]
    # resume: the member table restores exactly and the run completes
    agents, report = run_population(duo_fleet, params, out_dir=crash_dir,
                                    chunk_steps=512, config=cfg)
    assert report["status"] == "completed"
    members = {m["member"]: m for m in report["members"]}
    for member, seed, reseed, status, retries in table_before:
        assert members[member]["seed"] == seed
        assert members[member]["status"] == status == "active"
        assert members[member]["retries_left"] == retries
    # both stages present in each member's history after the resume
    for m in members.values():
        assert [h["stage"] for h in m["history"]
                if h["outcome"] == "completed"] == [0, 1]
    _step, final_man = load_population_manifest(crash_dir)
    assert final_man["next_stage"] == 2
    # golden: crash + resume == the uninterrupted run, learner for
    # learner — in particular the interval-0 graft survived the crash
    clean_dir = os.path.join(td, "clean")
    agents_c, report_c = run_population(duo_fleet, params,
                                        out_dir=clean_dir,
                                        chunk_steps=512, config=cfg)
    assert report_c["status"] == "completed"
    from conftest import tree_mismatches

    for k in agents_c:
        assert tree_mismatches(agents[k].sac, agents_c[k].sac) == [], \
            f"member {k}: crash+resume must train the same experiment " \
            "as the uninterrupted run"
    assert [e["member"] for e in report["leaderboard"]] == \
        [e["member"] for e in report_c["leaderboard"]]


def test_population_corrupt_store_culled_and_replaced(duo_fleet, tmp_path):
    """A member whose ENTIRE checkpoint store is corrupt has nothing to
    roll back to: it is culled (quarantine log records the reason) and
    replaced by a reseeded clone of the survivor — the population still
    completes."""
    td = str(tmp_path)
    cur = dataclasses.replace(TINY_CUR, stages=ramp_stages(2))
    params = chaos_params(faults=FaultParams(curriculum=cur))
    cfg = PopulationConfig(n_members=2, member_retries=2,
                           exploit_quantile=0.0, **POP_CFG_KW)

    def rot_member0_store():
        ck = os.path.join(td, "member_00", "ck")
        for seg in os.listdir(ck):
            store = os.path.join(ck, seg)
            for s in steps(store):
                _corrupt_first_payload(os.path.join(store, step_dirname(s)))

    # trip member 0 in stage 1 (its stage-0 checkpoints exist by then),
    # with its whole store bit-rotted right before the trip
    class TripAtStage1(DivergenceMonitor):
        def __init__(self):
            super().__init__(member=0)
            self.calls = 0

        def check(self, chunk, metrics):
            self.calls += 1
            if self.calls > 1:
                rot_member0_store()
                self._trip(chunk, "forced divergence onto a rotten store")

    agents, report = run_population(
        duo_fleet, params, out_dir=td, chunk_steps=512, config=cfg,
        monitors={0: TripAtStage1()})
    assert report["status"] == "completed"
    culls = [q for q in report["quarantine"] if q.get("action") == "culled"]
    assert len(culls) == 1 and culls[0]["member"] == 0
    m0 = [m for m in report["members"] if m["member"] == 0][0]
    assert m0["status"] == "active", "culled member must be REPLACED"
    assert m0["generation"] == 1
    events = [ev["event"] for ev in m0["lineage"]]
    assert "culled" in events and "replaced" in events
    cull_ev = [ev for ev in m0["lineage"] if ev["event"] == "culled"][0]
    assert "corrupt" in cull_ev["reason"]


def test_population_size1_degenerates_to_campaign(duo_fleet, tmp_path):
    """n_members=1 IS the serial campaign: same attempt sequence (stage,
    reseed, outcome), same trained learner bit-for-bit, golden-compared
    campaign_summary.json fields."""
    from distributed_cluster_gpus_tpu.rl.campaign import (CampaignConfig,
                                                          run_campaign)

    td = str(tmp_path)
    camp_dir = os.path.join(td, "campaign")
    state, agent, camp = run_campaign(
        duo_fleet, chaos_params(), out_dir=camp_dir,
        ckpt_dir=os.path.join(camp_dir, "ck"), chunk_steps=512,
        config=CampaignConfig(retries=1, backoff_s=0.0),
        monitor=TripMemberOnce())
    pop_dir = os.path.join(td, "pop")
    agents, pop = run_population(
        duo_fleet, chaos_params(), out_dir=pop_dir, chunk_steps=512,
        config=PopulationConfig(n_members=1, member_retries=1,
                                exploit_quantile=0.0, **POP_CFG_KW),
        monitors={0: TripMemberOnce(member=0)})
    # campaign_summary.json golden fields
    doc = json.load(open(os.path.join(camp_dir, "campaign_summary.json")))
    assert doc["schema_version"] == 1
    m0 = pop["members"][0]
    assert pop["n_stages"] == doc["n_stages"]
    assert [(h["stage"], h["reseed"], h["outcome"]) for h in m0["history"]] \
        == [(a["stage"], a["reseed"], a["outcome"])
            for a in doc["attempts"]]
    # the trained learner is the SAME learner, bit-for-bit
    from conftest import tree_mismatches

    assert int(agents[0].sac.step) == int(agent.sac.step) > 0
    assert tree_mismatches(agents[0].sac, agent.sac) == []

"""Test harness: run everything on a virtual 8-device CPU mesh.

Set platform/device-count env BEFORE jax is imported anywhere, so multi-chip
sharding tests (`shard_map`/pjit over a Mesh) run without TPU hardware —
the standard JAX way to test "multi-node without a cluster".
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The TPU-tunnel plugin (axon) force-selects itself via jax.config at
# sitecustomize time, overriding JAX_PLATFORMS; override it back so tests run
# on the virtual 8-device CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compilation cache (shared with bench.py): the suite is
# compile-dominated, and re-runs of unchanged programs load from cache
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    jax.config.update("jax_compilation_cache_max_size", 2 * 1024**3)
except Exception:  # noqa: BLE001 - cache is an optimization only
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# quick/slow tiers.  `pytest -m quick` is the ~2-minute smoke tier covering
# every subsystem; the tests below (measured >= ~15 s on the round-4
# baseline timing run — compile-heavy trainers, golden A/B double-compiles,
# long-horizon runs) carry `slow` and everything else is auto-marked
# `quick`.  Parametrized cases match on the bare nodeid (no [param]).
# ---------------------------------------------------------------------------

SLOW_TESTS = {
    "tests/test_aux_components.py::test_offline_builder_roundtrip",
    "tests/test_bench_evidence.py::test_cost_model_tiny_config",
    "tests/test_checkpoint.py::test_roundtrip_sac_and_sim",
    "tests/test_elastic.py::test_cached_physics_after_elastic",
    "tests/test_elastic.py::test_first_finish_preempts_remaining",
    "tests/test_elastic.py::test_gpu_accounting_consistent",
    "tests/test_elastic.py::test_progress_preserved_across_preemption",
    "tests/test_engine.py::test_arrival_pregen_poisson_same_workload",
    "tests/test_engine.py::test_arrival_pregen_scan_fallback_bit_identical",
    "tests/test_engine.py::test_arrival_pregen_sinusoid_statistical_match",
    "tests/test_engine.py::test_cached_physics_matches_recompute",
    "tests/test_engine.py::test_cap_greedy_reduces_power",
    "tests/test_engine.py::test_carbon_cost_equals_joint_nf_when_price_positive",
    "tests/test_engine.py::test_default_policy_energy_aware_inference",
    "tests/test_engine.py::test_determinism",
    "tests/test_engine.py::test_eco_route_routes_to_min_energy_dc",
    "tests/test_engine.py::test_grid_admission_honors_gpu_cap",
    "tests/test_engine.py::test_reserve_inf_gpus_blocks_training",
    "tests/test_engine.py::test_reserve_inf_gpus_chsac_masks",
    "tests/test_engine.py::test_vmap_rollouts_distinct",
    "tests/test_evaluation.py::test_compare_same_workload_joint_nf_saves_energy",
    "tests/test_evaluation.py::test_compare_seeds_aggregate_shape",
    "tests/test_evaluation.py::test_variant_3c_breaks_carbon_cost_degeneracy",
    "tests/test_evaluation.py::test_variant_steady_state_no_drops",
    "tests/test_parallel.py::TestDCNMesh::test_ppo_on_dcn_mesh",
    "tests/test_parallel.py::test_rollout_bit_parity_across_mesh_sizes",
    "tests/test_parallel.py::test_aggregate_throughput_scales_with_devices",
    "tests/test_parallel.py::TestDCNMesh::test_trainer_on_dcn_mesh_matches_flat_mesh",
    "tests/test_parallel.py::TestDistributedTrainer::test_progresses_and_learns",
    "tests/test_parallel.py::test_batched_init_independent_streams",
    "tests/test_parallel.py::test_gradient_allreduce_matches_single_device",
    "tests/test_ppo.py::test_invalid_rows_carry_no_gradient",
    "tests/test_ppo.py::test_sharded_ppo_trainer",
    "tests/test_ppo.py::test_update_finite_and_moves_params",
    "tests/test_queue_rings.py::test_chsac_ring_runs_and_queues",
    "tests/test_queue_rings.py::test_ring_matches_slab_when_no_overflow",
    "tests/test_queue_rings.py::test_tiny_slab_big_backlog_zero_drops",
    "tests/test_rl.py::TestAlphaCap::test_alpha_max_caps_temperature",
    "tests/test_rl.py::TestAlphaCap::test_default_config_bounds_alpha",
    "tests/test_rl.py::TestOfflineTraining::test_pretrain_from_npz",
    "tests/test_rl.py::TestOnlineTraining::test_short_chsac_run_trains",
    "tests/test_rl.py::TestPolicyTail::test_deferred_route_commits_same_step",
    "tests/test_rl.py::TestReplay::test_mixed_validity_ring_invariants",
    "tests/test_rl.py::TestReplay::test_offline_npz_reference_obs_keys",
    "tests/test_rl.py::TestReplay::test_offline_npz_roundtrip",
    "tests/test_rl.py::TestReplay::test_ring_wrap",
    "tests/test_rl.py::TestReplay::test_scatter_only_valid",
    "tests/test_rl.py::TestReplay::test_warmup_gate_survives_ring_plateau",
    "tests/test_rl.py::TestSAC::test_lambda_raises_effective_penalty",
    "tests/test_rl.py::TestSAC::test_target_polyak_lag",
    "tests/test_rl.py::TestSAC::test_update_finite_and_advances",
    "tests/test_rl.py::TestSACHeadsCritic::test_update_finite_and_advances",
    # round 7: compiles three full engine programs (1-dev vmap + shard_map
    # + the parity baseline) — the unified-body bit coverage tier-1 needs
    # is already carried by the K goldens
    "tests/test_superstep.py::test_superstep_shard_parity",
    # round 19: the twin goldens re-run full sims (3-segment vs batch,
    # 5 forecast lanes vs serial run_algo, SIGKILL subprocess resume) —
    # the quick tier keeps cursor validation, fork purity, the service
    # dispatch, and the satellite CLIs as its smoke coverage
    "tests/test_twin.py::test_incremental_matches_batch",
    "tests/test_twin.py::test_forecast_golden_t0_zero",
    "tests/test_twin.py::test_sigkill_mid_ingest_resumes_byte_identical",
    # (and the two mid-weight resume/RCA pins — the SIGKILL golden
    # above exercises both paths more deeply)
    "tests/test_twin.py::test_fingerprint_mismatch_refuses_resume",
    "tests/test_twin.py::test_rca_window_reproduces_history",
    # round 19 (tier-1 budget rebalance): the quick tier crossed the
    # 870s verify wall (1008s measured on this box), so the heaviest
    # remaining goldens with duplicated coverage move to the slow tier:
    # 3 of the 5 K goldens (quick keeps default_policy-ring-4 — the
    # canonical algo/layout/K — and carbon_cost-slab-2 for the slab
    # layout + K=2), the serial arm of the pipelined-CSV byte pair
    # (depth-4 stays quick), the obs eqn-overhead pin (obs CSV
    # byte-identity stays quick in test_obs and bench.py banks the
    # realized overhead per round), the op-census smoke (the per-class
    # eqn budgets stay quick), the legacy workload-spec byte golden
    # (test_signals_legacy_equivalence already rides slow), and the
    # sharded SAC state test (its test_parallel siblings already ride
    # slow)
    "tests/test_superstep.py::test_golden_bit_identical_across_k[eco_route-ring-4]",
    "tests/test_superstep.py::test_golden_bit_identical_across_k[joint_nf-ring-8]",
    "tests/test_superstep.py::test_golden_bit_identical_across_k[default_policy-slab-4]",
    "tests/test_io_pipeline.py::test_pipelined_csv_bytes_match_serial[1]",
    "tests/test_perf_structure.py::test_obs_on_eqn_overhead_pinned",
    "tests/test_perf_structure.py::test_op_census_smoke",
    "tests/test_workload.py::test_legacy_spec_byte_identical",
    "tests/test_parallel.py::TestDistributedTrainer::test_sac_replicated_states_sharded",
    # (second pass, same rebalance: still ~30s over the wall) the
    # fault/bandit fastpath eqn ceiling, the select-free structural pin
    # (test_superstep_per_event_eqn_budget still pins the fused body's
    # eqn count quick), the cap-controller golden and the pregen-off
    # multichunk golden (both regimes keep slow-tier goldens and the
    # quick K goldens exercise the same fused body)
    "tests/test_perf_structure.py::test_fault_and_bandit_fastpath_budget",
    "tests/test_perf_structure.py::test_superstep_program_is_select_free",
    "tests/test_superstep.py::test_golden_power_cap_controller",
    "tests/test_superstep.py::test_golden_multichunk_pregen_off",
    # round 10: the chunk-boundary continuity pin runs ~10 full sims
    # (three regimes x K) — the quick-tier K goldens already carry the
    # bit-identity coverage
    "tests/test_superstep.py::test_chunk_boundary_continuity_exact",
    # round 10: week-scale one-scan workload run (J=8192, ~3e5 events)
    "tests/test_workload.py::test_week_scale_one_scan_j8192",
    "tests/test_workload.py::test_signals_legacy_equivalence",
    # round 9: planner-vs-legacy A/B goldens double-compile every config;
    # since the round-19 budget rebalance the degenerate-pressure pair
    # rides slow too — the planner program has been the DEFAULT since
    # round 12, so every quick K golden exercises it; the static gate
    # stays quick as the smoke coverage
    "tests/test_write_plan.py::test_planner_bit_identical",
    "tests/test_write_plan.py::test_planner_bit_identical_degenerate_pressure",
    "tests/test_write_plan.py::test_planner_bit_identical_cap_controller",
    "tests/test_write_plan.py::test_planner_bit_identical_chsac",
    "tests/test_write_plan.py::test_planner_csv_and_metrics_bytes_unchanged",
    # round 12 (universal fast path): the forced-gate family goldens
    # double-compile full programs (legacy + fast arm each), so they all
    # ride the slow tier like the round-5 planner goldens — the quick
    # tier keeps the static-gate, eligibility-residue, and the
    # test_workload_signal_step_budget eqn ceiling as its smoke
    # coverage (the fault/bandit eqn ceiling moved to the slow tier in
    # the round-19 budget rebalance)
    "tests/test_superstep.py::test_golden_faults_superstep",
    "tests/test_superstep.py::test_golden_signals_superstep",
    "tests/test_write_plan.py::test_planner_bit_identical_bandit",
    "tests/test_write_plan.py::test_planner_bit_identical_bandit_faults",
    "tests/test_write_plan.py::test_planner_bit_identical_faults",
    "tests/test_write_plan.py::test_planner_bit_identical_chsac_elastic",
    "tests/test_write_plan.py::test_planner_bit_identical_chsac_faults",
    # round 9: three full chsac training runs (golden + interrupt + resume)
    "tests/test_obs.py::test_metrics_jsonl_resume_roundtrip",
    # round 11 (chaos-native training): the campaign e2e runs two chsac
    # training segments (abort -> rollback -> reseeded retry), the
    # held-out sweep runs 3 presets x 3 algos incl. online chsac, and
    # the CLI/trainer shutdown tests compile full programs or drive a
    # cold subprocess — the quick tier keeps the curriculum lowering,
    # composition probes, gate logic, and flush-regression coverage
    "tests/test_campaign.py::test_campaign_abort_rollback_reseed_completion",
    "tests/test_campaign.py::test_campaign_budget_exhaustion_fails",
    # round 13 (population-based chaos training): each e2e drives several
    # full chsac training segments (N members x stages x retries) plus
    # the vmapped held-out leaderboard evals — the quick tier keeps the
    # manifest commit/crash-injection round-trips, the population
    # fsck/gc/bundle-resolver fixtures, and the score/draw/label logic
    "tests/test_population.py::test_population_fault_isolation_and_leaderboard_e2e",
    "tests/test_population.py::test_population_resume_from_manifest",
    "tests/test_population.py::test_population_corrupt_store_culled_and_replaced",
    "tests/test_population.py::test_population_size1_degenerates_to_campaign",
    # round 12 (verified checkpoint store + forensic replay): each replay
    # e2e compiles several engine programs (the bisection re-runs the
    # failing chunk at log2(chunk_steps) distinct prefix lengths, and the
    # clean replay runs a full chsac training twice) — the quick tier
    # keeps the whole crash-injection sweep (in-process fault points AND
    # the SIGKILL-mid-save subprocess: numpy-tree stores, no engine
    # compile), the fallback-chain walks, fsck +/-, and the abort-context
    # round-trips
    "tests/test_replay.py::test_watchdog_replay_reproduces_and_bisects",
    "tests/test_replay.py::test_divergence_abort_replays_and_bisects",
    "tests/test_replay.py::test_clean_replay_csv_byte_match",
    "tests/test_chaos.py::test_held_out_chaos_sweep_e2e",
    "tests/test_shutdown.py::test_trainer_sigterm_saves_checkpoint_and_status",
    "tests/test_shutdown.py::test_run_sim_cli_sigterm_exits_nonzero",
    "tests/test_wiring.py::TestFusedTrainSteps::test_caps_at_max",
    "tests/test_wiring.py::TestFusedTrainSteps::test_runs_requested_updates",
    "tests/test_wiring.py::TestFusedTrainSteps::test_warmup_gates_to_zero",
    "tests/test_wiring.py::TestOfflineDatasetCLI::test_offline_pretrain_e2e",
    "tests/test_wiring.py::TestPPOCLI::test_ppo_cli_writes_csvs",
    "tests/test_wiring.py::TestRolloutsCLI::test_distributed_cli_writes_csvs",
    "tests/test_wiring.py::TestRouterWeightsCLI::test_latency_only_weights_route_to_nearest_dc",
    "tests/test_wiring.py::TestRouterWeightsCLI::test_queue_weight_spreads_load",
    "tests/test_wiring.py::TestSameWorkloadAcrossAlgos::test_arrival_streams_identical",
    "tests/test_wiring.py::TestTimeDtype::test_chsac_replay_ingest_under_x64",
    "tests/test_wiring.py::TestTimeDtype::test_long_horizon_latency_resolution",
    # round 13 (dcg-lint): every test that traces a real engine config
    # rides the slow tier (this container is single-core and the tier-1
    # budget is tight) — the quick tier keeps the sub-second fabricated
    # per-rule positive/negative pairs (each shipped rule demonstrably
    # catches its violation), the registry/allowlist hygiene checks,
    # the walker-equivalence pin, and the baselines schema check; the
    # canonical matrix itself is additionally enforced by
    # scripts/lint_graph.py (banked per round by bench.py)
    # round 16 (sweep grid): the paper-fleet serial-vs-grid golden
    # compiles + runs 4 config-4 programs twice (grid arm + serial
    # refs), and the two subprocess tests each pay a cold interpreter +
    # cold-process compiles — since the round-19 budget rebalance BOTH
    # serial-vs-grid goldens ride the slow tier (engine bit-identity
    # stays quick via the K goldens); the quick tier keeps the columnar
    # round-trips, the validator, and the cell_key contract
    "tests/test_sweep.py::test_grid_bit_identical_duo",
    "tests/test_sweep.py::test_grid_bit_identical_paper_fleet",
    "tests/test_sweep.py::test_sigkill_mid_grid_resumes_missing_buckets",
    "tests/test_sweep.py::test_chaos_sweep_argv_note_and_key_fields",
    "tests/test_lint.py::test_canonical_full_matrix_lints_clean",
    "tests/test_lint.py::test_update_baselines_roundtrips_byte_identical",
    "tests/test_lint.py::test_canonical_joint_nf_lints_clean",
    "tests/test_lint.py::test_in_tree_baseline_matches_live_trace",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        bare = item.nodeid.split("[")[0]
        # an exact (param-qualified) nodeid wins over the bare lookup so
        # single parametrizations of a golden can ride the slow tier
        # while their siblings stay quick
        if item.nodeid in SLOW_TESTS or bare in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
        else:
            item.add_marker(pytest.mark.quick)


@pytest.fixture(scope="module", autouse=True)
def _clear_jax_caches_between_modules():
    """Drop live compiled executables after each test module.

    The suite compiles hundreds of engine programs into one process;
    on this container's XLA CPU build the accumulated live-executable
    state eventually segfaults a later tiny-op compile (observed on the
    PRISTINE seed too — `backend_compile` dies inside `init_state` /
    `make_jaxpr` mid-suite, position wandering with cache warmth).
    Releasing executables at module boundaries keeps the backend's
    live-program count bounded; re-compiles of still-live module
    fixtures are transparent and mostly served by the persistent disk
    cache."""
    yield
    jax.clear_caches()


def tree_mismatches(a, b):
    """Key-paths of leaves that differ BITWISE between two pytrees (PRNG
    keys compared via key_data; NaNs equal).  THE one bit-identity
    comparator the golden suites share — test_superstep, test_engine,
    and test_workload all pin the same contract, so they must compare
    with the same rule."""
    import jax
    import jax.numpy as jnp

    bad = []

    def eq(path, x, y):
        if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        if not np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True):
            bad.append(jax.tree_util.keystr(path))

    jax.tree_util.tree_map_with_path(eq, a, b)
    return bad


@pytest.fixture(scope="session")
def fleet():
    from distributed_cluster_gpus_tpu.configs import build_fleet

    return build_fleet()


@pytest.fixture(scope="session")
def single_dc_fleet():
    from distributed_cluster_gpus_tpu.configs import build_single_dc_fleet

    return build_single_dc_fleet()


@pytest.fixture
def rng():
    return np.random.default_rng(0)

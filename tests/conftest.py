"""Test harness: run everything on a virtual 8-device CPU mesh.

Set platform/device-count env BEFORE jax is imported anywhere, so multi-chip
sharding tests (`shard_map`/pjit over a Mesh) run without TPU hardware —
the standard JAX way to test "multi-node without a cluster".
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The TPU-tunnel plugin (axon) force-selects itself via jax.config at
# sitecustomize time, overriding JAX_PLATFORMS; override it back so tests run
# on the virtual 8-device CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def fleet():
    from distributed_cluster_gpus_tpu.configs import build_fleet

    return build_fleet()


@pytest.fixture(scope="session")
def single_dc_fleet():
    from distributed_cluster_gpus_tpu.configs import build_single_dc_fleet

    return build_single_dc_fleet()


@pytest.fixture
def rng():
    return np.random.default_rng(0)

"""Queue rings (queue_mode="ring", the round-4 default layout).

Waiting jobs leave the JobSlab for per-(DC, jtype) FIFO rings
(`models/structs.py::QueueRings`), which (a) keeps the per-step O(J) slab
ops independent of backlog depth and (b) restores the reference's
unbounded-queue overload semantics (`/root/reference/simcore/models.py:
61-62` queues every arrival; the old all-in-slab layout dropped them once
the slab filled).  These tests pin:

* ring == slab bit-exactness when queues never overflow the slab
  (single-ingress config, so xfer-completion order == seq order and the
  two layouts' FIFO disciplines coincide);
* zero drops + full completion accounting when the slab is far smaller
  than the backlog (the slab-mode failure shape);
* FIFO pop order and inference priority;
* ring-overflow drop accounting;
* O(1) queue-length counters against a slab recount.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import JobStatus, QRec, SimParams
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
from distributed_cluster_gpus_tpu.sim.io import run_simulation


def _params(**kw):
    base = dict(algo="default_policy", duration=400.0, log_interval=20.0,
                inf_mode="poisson", inf_rate=1.0,
                trn_mode="poisson", trn_rate=0.02,
                job_cap=96, queue_cap=128, lat_window=256)
    base.update(kw)
    return SimParams(**base)


def _run(fleet, p, chunk_steps=512):
    return run_simulation(fleet, p, out_dir=None, chunk_steps=chunk_steps)


@pytest.mark.parametrize("algo", ["default_policy", "joint_nf", "bandit"])
def test_ring_matches_slab_when_no_overflow(single_dc_fleet, algo):
    """Single ingress, ample slab: the layouts must realize the SAME run.

    (Multi-ingress runs can legitimately differ: slab mode pops the
    lowest-seq queued job, rings pop in xfer-completion order — the
    reference's append/pop(0).  With one ingress the orders coincide.)
    """
    outs = {}
    for mode in ("ring", "slab"):
        p = _params(algo=algo, queue_mode=mode, inf_rate=3.0)
        st = _run(single_dc_fleet, p)
        outs[mode] = st
    a, b = outs["ring"], outs["slab"]
    assert int(a.n_dropped) == 0 and int(b.n_dropped) == 0
    np.testing.assert_array_equal(np.asarray(a.n_finished),
                                  np.asarray(b.n_finished))
    np.testing.assert_allclose(np.asarray(a.dc.energy_j),
                               np.asarray(b.dc.energy_j), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(a.units_finished),
                               np.asarray(b.units_finished), rtol=1e-6)
    # latency windows: same pushes in the same order
    np.testing.assert_array_equal(np.asarray(a.lat.count),
                                  np.asarray(b.lat.count))
    np.testing.assert_allclose(np.asarray(a.lat.buf),
                               np.asarray(b.lat.buf), rtol=1e-6)


def test_tiny_slab_big_backlog_zero_drops(single_dc_fleet):
    """The slab-mode failure shape: backlog >> job_cap.

    With job_cap far below the arrival volume, slab mode drops most
    arrivals; ring mode must queue every one (no drops) and conservation
    must hold: arrivals == finished + still-waiting + still-placed."""
    p = _params(algo="default_policy", queue_mode="ring", inf_rate=8.0,
                trn_rate=0.3, duration=300.0, job_cap=16, queue_cap=4096)
    st = _run(single_dc_fleet, p)
    assert int(st.n_dropped) == 0
    arrivals = int(np.asarray(st.arr_count).sum() - 2)  # one primed draw/stream
    finished = int(np.asarray(st.n_finished).sum())
    waiting = int(np.asarray(st.queues.tail - st.queues.head).sum())
    placed = int(np.asarray(
        (st.jobs.status != JobStatus.EMPTY)).sum())
    assert finished > 0 and waiting > 0  # genuinely backlogged
    assert arrivals == finished + waiting + placed

    p_slab = dataclasses.replace(p, queue_mode="slab")
    st_slab = _run(single_dc_fleet, p_slab)
    assert int(st_slab.n_dropped) > 0  # the shape ring mode fixes


def test_ring_overflow_counts_drops(single_dc_fleet):
    # training jobs (~50k units) can't finish within the run, so the train
    # ring must overflow its 8 slots and count drops
    p = _params(algo="default_policy", queue_mode="ring", inf_rate=0.5,
                trn_rate=0.5, duration=300.0, job_cap=16, queue_cap=8)
    st = _run(single_dc_fleet, p)
    assert int(st.n_dropped) > 0


def test_ring_fifo_and_inference_priority(fleet):
    """Push A then B into one ring -> A pops first; inf ring beats train."""
    p = _params(algo="default_policy", queue_mode="ring", queue_cap=8)
    eng = Engine(fleet, p)
    st = init_state(jax.random.key(0), fleet, p)

    def rec(seq, size=5.0):
        return eng._rec_pack(st.t.dtype, size, seq, 0, 0.0, 0.0, 0.0)

    dcj = jnp.int32(0)
    push = jax.jit(lambda s, jt, r: eng._ring_push(
        s, dcj, jnp.int32(jt), r, jnp.bool_(True)))
    st = push(st, 1, rec(7))   # train seq 7 first
    st = push(st, 0, rec(11))  # then inf seq 11
    st = push(st, 0, rec(12))

    rec0, jt, found = jax.jit(lambda s: eng._ring_head(s, dcj))(st)
    assert bool(found) and int(jt) == 0  # inf priority despite train first
    assert int(rec0[QRec.SEQ]) == 11    # FIFO within the inf ring
    st = eng._ring_pop(st, dcj, jt, jnp.bool_(True))
    rec1, jt1, _ = eng._ring_head(st, dcj)
    assert int(jt1) == 0 and int(rec1[QRec.SEQ]) == 12
    st = eng._ring_pop(st, dcj, jt1, jnp.bool_(True))
    rec2, jt2, found2 = eng._ring_head(st, dcj)
    assert bool(found2) and int(jt2) == 1 and int(rec2[QRec.SEQ]) == 7


def test_queue_lens_match_ring_counters(single_dc_fleet):
    """O(1) counter lengths == an explicit head/tail recount mid-run."""
    p = _params(algo="default_policy", queue_mode="ring", inf_rate=2.0,
                trn_rate=0.5, duration=120.0, job_cap=16, queue_cap=2048)
    eng = Engine(single_dc_fleet, p)
    st = init_state(jax.random.key(3), single_dc_fleet, p)
    st, _ = eng.run_chunk(st, None, 2048)
    q_inf, q_trn = eng._queue_lens(st)
    cnt = np.asarray(st.queues.tail - st.queues.head)
    np.testing.assert_array_equal(np.asarray(q_inf), cnt[:, 0])
    np.testing.assert_array_equal(np.asarray(q_trn), cnt[:, 1])
    assert cnt.min() >= 0
    assert int(np.asarray(q_trn).sum()) > 0  # the run is backlogged


def test_chsac_ring_runs_and_queues(fleet):
    """chsac_af end-to-end in ring mode: training happens, queues cycle."""
    from distributed_cluster_gpus_tpu.rl.train import train_chsac

    p = SimParams(algo="chsac_af", duration=150.0, log_interval=20.0,
                  inf_mode="sinusoid", inf_rate=1.0,
                  trn_mode="poisson", trn_rate=0.05,
                  rl_warmup=64, rl_batch=64, job_cap=128, queue_cap=64,
                  queue_mode="ring", lat_window=256)
    st, agent, _ = train_chsac(fleet, p, out_dir=None, chunk_steps=512)
    assert int(np.asarray(st.n_finished).sum()) > 0
    assert int(agent.sac.step) > 0
    assert np.asarray(st.queues.tail - st.queues.head).min() >= 0


def test_auto_queue_cap_sizing(fleet):
    """Drop-free auto sizing: covers the run's total arrivals with margin,
    floors/clamps sanely, and scales the memory guard with rollouts and
    the time dtype (week runs carry float64 records)."""
    from distributed_cluster_gpus_tpu.sim.engine import auto_queue_cap

    # canonical week: trn-only 0.02/s x 8 ingresses x 604800 s ~ 96,768
    week = SimParams(algo="joint_nf", duration=604_800.0, inf_mode="off",
                     trn_mode="poisson", trn_rate=0.02,
                     time_dtype="float64")
    q = auto_queue_cap(week, fleet)
    assert q >= int(604_800 * 0.16 * 1.3)  # absorbs every arrival + margin
    # short steady-state runs stay near the 1024 floor
    short = SimParams(algo="joint_nf", duration=60.0, inf_mode="poisson",
                      inf_rate=1.0, trn_mode="off")
    assert 1024 <= auto_queue_cap(short, fleet) <= 1664
    # unbounded-duration shapes hit the hard clamp, not infinity
    forever = SimParams(algo="joint_nf", duration=1e9,
                        inf_mode="sinusoid", inf_rate=6.0,
                        trn_mode="poisson", trn_rate=0.1)
    assert auto_queue_cap(forever, fleet) <= 1 << 18
    # more rollouts -> tighter memory guard (never larger)
    assert auto_queue_cap(week, fleet, rollouts=64) <= auto_queue_cap(
        week, fleet, rollouts=1)

"""bench.best_prior_on_chip: the round-end CPU-fallback's evidence scan.

This runs in the driver-critical end-of-round path (after measure() has
already succeeded), so the contract under test is: cite only comparable
full-pipeline on-chip runs (key/sweep, never ablations), prefer the
strongest row, and never raise on missing/corrupt/foreign files.
"""

import json
import os

import bench


def _write(root, name, payload):
    os.makedirs(os.path.join(root, "bench_results"), exist_ok=True)
    path = os.path.join(root, "bench_results", name)
    with open(path, "w") as f:
        if isinstance(payload, str):
            f.write(payload)
        else:
            json.dump(payload, f)


class TestBestPriorOnChip:
    def test_empty_dir_returns_none(self, tmp_path):
        assert bench.best_prior_on_chip(root=str(tmp_path)) is None

    def test_missing_bench_results_dir_returns_none(self, tmp_path):
        assert bench.best_prior_on_chip(root=str(tmp_path / "nope")) is None

    def test_key_configs_measured_best_row_wins(self, tmp_path):
        _write(tmp_path, "key_r03.json", {
            "platform": "tpu", "value": 88000.5,
            "config": {"rollouts": 256, "job_cap": 128},
            "configs_measured": [
                {"rollouts": 256, "job_cap": 128, "events_per_sec": 88000.5},
                {"rollouts": 256, "job_cap": 512, "events_per_sec": 61000.0},
            ]})
        best = bench.best_prior_on_chip(root=str(tmp_path))
        assert best["events_per_sec"] == 88000.5
        assert best["rollouts"] == 256 and best["job_cap"] == 128
        assert best["file"] == os.path.join("bench_results", "key_r03.json")

    def test_sweep_rows_and_axon_platform_accepted(self, tmp_path):
        _write(tmp_path, "sweep_r03.json", {
            "platform": "axon", "value": 70000.0,
            "sweep": [
                {"rollouts": 128, "job_cap": 128, "events_per_sec": 90000.0},
                {"rollouts": 512, "job_cap": 512, "events_per_sec": 70000.0},
            ]})
        best = bench.best_prior_on_chip(root=str(tmp_path))
        assert best["events_per_sec"] == 90000.0

    def test_plain_value_fallback_uses_config(self, tmp_path):
        _write(tmp_path, "key_r03.json", {
            "platform": "tpu", "value": 50000.0,
            "config": {"rollouts": 64, "job_cap": 8192}})
        best = bench.best_prior_on_chip(root=str(tmp_path))
        assert best["events_per_sec"] == 50000.0
        assert best["job_cap"] == 8192

    def test_ablations_never_cited(self, tmp_path):
        _write(tmp_path, "ablate_notrain_r03.json", {
            "platform": "tpu", "value": 999999.0,
            "config": {"rollouts": 256, "job_cap": 512}})
        _write(tmp_path, "key_r03.json", {
            "platform": "tpu", "value": 80000.0,
            "config": {"rollouts": 256, "job_cap": 128}})
        best = bench.best_prior_on_chip(root=str(tmp_path))
        assert best["events_per_sec"] == 80000.0

    def test_cpu_fallback_files_ignored(self, tmp_path):
        _write(tmp_path, "key_r03.json", {"platform": "cpu", "value": 20000.0})
        assert bench.best_prior_on_chip(root=str(tmp_path)) is None

    def test_corrupt_and_foreign_shapes_never_raise(self, tmp_path):
        _write(tmp_path, "key_r03.json", "not json {")
        _write(tmp_path, "sweep_r03.json", {
            "platform": "tpu", "sweep": [{"rollouts": 1}]})  # missing ev/s
        assert bench.best_prior_on_chip(root=str(tmp_path)) is None

    def test_top_level_array_never_raises(self, tmp_path):
        _write(tmp_path, "key_r03.json", "[1, 2, 3]")
        assert bench.best_prior_on_chip(root=str(tmp_path)) is None


def test_cost_model_tiny_config():
    """The bench's analytical cost section: compiles the tiny pipeline AOT
    and checks per-event FLOPs/bytes and the v5e roofline reduction are
    positive and internally consistent (VERDICT r04 item 1)."""
    trainer, n_rollouts, n_dev = bench._make_trainer(4, 32)
    chunk_steps = 16
    trainer._step_fns[chunk_steps] = trainer._build_step(chunk_steps)
    cm = bench.cost_model(trainer, chunk_steps, n_rollouts * chunk_steps,
                          0.0, "cpu", n_dev)
    assert cm is not None
    assert cm["per_event"]["flops"] > 0 and cm["per_event"]["hbm_bytes"] > 0
    rl = cm["v5e_roofline_per_chip"]
    assert rl["bound_ev_s"] == min(rl["compute_bound_ev_s"],
                                   rl["bandwidth_bound_ev_s"])
    assert rl["binding"] in ("hbm", "mxu")
    # no measured section off-chip
    assert "measured" not in cm
    # on-chip labeling adds the measured utilization block
    cm2 = bench.cost_model(trainer, chunk_steps, n_rollouts * chunk_steps,
                           1000.0, "tpu", n_dev)
    m = cm2["measured"]
    assert 0 < m["mfu"] < 1 and 0 < m["roofline_attainment"]

"""sweep/ — grid spec, resume keying, columnar artifact, one-program
compiler (round 16).

The correctness anchor: every cell summary produced by the bucketed
one-program grid must match the serial ``run_algo`` row bit-for-bit
(the duo golden in the quick tier, the paper fleet in the slow tier).
Around it: the ``cell_key`` resume contract in BOTH directions (legacy
rows still resume; changed seed/duration/mttr recomputes), the spec
validator, the binary columnar round-trip, the SIGKILL-mid-grid resume,
and the ledger's ``sweep_grid`` record kind.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from distributed_cluster_gpus_tpu.sweep import (  # noqa: E402
    columnar, spec)
from distributed_cluster_gpus_tpu.sweep.spec import (  # noqa: E402
    DEFAULT_DURATION, DEFAULT_MTTR, DEFAULT_SEED, SweepGrid, cell_key,
    grid_cells, grid_from_dict, validate_grid)


# ---------------------------------------------------------------------------
# cell_key: the ONE resume rule, both directions
# ---------------------------------------------------------------------------

def test_cell_key_distinguishes_seed_duration_mttr():
    base = {"rate": 1.0, "preset": None, "algo": "eco_route",
            "seed": 123, "duration": 600.0, "mttr": 300.0}
    assert cell_key(dict(base)) == cell_key(dict(base))
    for field, other in (("seed", 7), ("duration", 900.0),
                         ("mttr", 120.0)):
        changed = dict(base, **{field: other})
        assert cell_key(changed) != cell_key(base), field


def test_cell_key_legacy_rows_resume_default_invocation():
    """Direction 1: a pre-round-16 row (no seed/duration/mttr fields)
    must key identically to the flag-less default invocation's row —
    an old artifact still resumes it."""
    legacy = {"rate": 2.0, "preset": None, "algo": "default_policy"}
    modern = dict(legacy, seed=DEFAULT_SEED, duration=DEFAULT_DURATION,
                  mttr=DEFAULT_MTTR)
    assert cell_key(legacy) == cell_key(modern)

    # direction 2: a non-default re-run must NOT collide with the
    # legacy row — it computes instead of skipping
    assert cell_key(dict(legacy, seed=7)) != cell_key(legacy)
    assert cell_key(dict(legacy, duration=900.0)) != cell_key(legacy)
    assert cell_key(dict(legacy, mttr=60.0)) != cell_key(legacy)


def test_cell_key_axes_and_defaults_pinned():
    # preset cells key on the preset axis even with rate=None present
    pr = {"rate": None, "preset": "rolling_blackout", "algo": "bandit",
          "stage": 1}
    assert cell_key(pr)[0] == "preset:rolling_blackout"
    # the defaults are the chaos_sweep argparse/paper constants — if
    # either drifts, legacy resume silently breaks
    from distributed_cluster_gpus_tpu.configs.paper import CHAOS_MTTR_S

    assert DEFAULT_MTTR == CHAOS_MTTR_S
    assert DEFAULT_SEED == 123
    assert DEFAULT_DURATION == 600.0


def test_chaos_sweep_reexports_canonical_key():
    """chaos_sweep.py must share the ONE keying rule (not a fork)."""
    sys.path.insert(0, os.path.join(HERE, "scripts"))
    try:
        import chaos_sweep
    finally:
        sys.path.pop(0)
    assert chaos_sweep.cell_key is cell_key
    assert chaos_sweep.load_done is spec.load_done


# ---------------------------------------------------------------------------
# spec validation
# ---------------------------------------------------------------------------

def test_grid_from_dict_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown sweep spec key"):
        grid_from_dict({"rates": [1.0], "chaos": "yes"})
    with pytest.raises(TypeError):
        grid_from_dict({"rates": 1.0})
    with pytest.raises(TypeError):
        grid_from_dict([1, 2])


def test_validate_grid_flags_violations():
    bad = SweepGrid(axis="rates", rates=(-1.0,), algos=("nope",),
                    seeds=(1.5,), fleet="mega", duration=-5.0)
    errs = validate_grid(bad, where="t")
    joined = "\n".join(errs)
    for needle in ("rate", "algo", "seed", "fleet", "duration"):
        assert needle in joined, (needle, errs)
    assert validate_grid(SweepGrid(), where="t") == []


def test_grid_cells_order_and_row_ids():
    g = SweepGrid(axis="rates", rates=(0.0, 1.0),
                  algos=("default_policy", "eco_route"), seeds=(1, 2),
                  fleet="duo", duration=60.0, mttr=120.0)
    cells = grid_cells(g)
    assert len(cells) == 8
    ids = [c.row_id() for c in cells]
    # every row carries the resume-key fields
    for r in ids:
        assert r["seed"] in (1, 2) and r["duration"] == 60.0
        assert r["mttr"] == 120.0 and r["fleet"] == "duo"
    assert len({cell_key(r) for r in ids}) == 8


# ---------------------------------------------------------------------------
# columnar artifact
# ---------------------------------------------------------------------------

def _motley_rows():
    return [
        {"algo": "a", "rate": 0.0, "seed": 1, "avail": 1.0,
         "p99": float("nan"), "mig": None, "flag": True, "n": 3},
        {"algo": "b", "rate": 2.0, "seed": 2, "avail": 0.5,
         "p99": 0.25, "extra": "only-here", "flag": False, "n": -1},
    ]


def test_columnar_shard_roundtrip_bytes(tmp_path):
    rows = _motley_rows()
    p = tmp_path / "s.dcgcol"
    columnar.write_shard(str(p), rows)
    back = columnar.read_shard(str(p))
    # byte-compare the strict-JSON serialization: ints stay ints,
    # bools stay bools, NaN/None/missing survive distinctly
    assert (json.dumps(back, sort_keys=True)
            == json.dumps(rows, sort_keys=True))
    assert "extra" not in back[0] and back[1]["extra"] == "only-here"
    assert back[0]["flag"] is True and back[1]["n"] == -1


def test_columnar_bucket_manifest_roundtrip(tmp_path):
    d = str(tmp_path / "col")
    rows = _motley_rows()
    columnar.write_bucket(d, [cell_key(r | {"preset": None})
                              for r in rows], rows)
    more = [{"algo": "c", "rate": 4.0, "seed": 3, "avail": 0.9}]
    columnar.write_bucket(d, [cell_key(more[0] | {"preset": None})],
                          more)
    man = json.load(open(os.path.join(d, columnar.MANIFEST)))
    assert man["schema"] == columnar.MANIFEST_SCHEMA
    assert len(man["shards"]) == 2
    back = columnar.read_rows(d, verify=True)
    assert (sorted(json.dumps(r, sort_keys=True) for r in back)
            == sorted(json.dumps(r, sort_keys=True)
                      for r in rows + more))
    # rewriting one bucket replaces its shard in place (resume path)
    columnar.write_bucket(d, [cell_key(more[0] | {"preset": None})],
                          more)
    assert len(json.load(open(os.path.join(
        d, columnar.MANIFEST)))["shards"]) == 2


def test_columnar_verify_catches_corruption(tmp_path):
    d = str(tmp_path / "col")
    rows = _motley_rows()
    columnar.write_bucket(d, ["k"], rows)
    shard = json.load(open(os.path.join(
        d, columnar.MANIFEST)))["shards"][0]["file"]
    path = os.path.join(d, shard)
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(ValueError, match="sha256|checksum|corrupt"):
        columnar.read_rows(d, verify=True)


# ---------------------------------------------------------------------------
# the correctness anchor: serial rows == grid rows, bit for bit
# ---------------------------------------------------------------------------

def _assert_grid_matches_serial(grid, tmp_path, chunk_steps=256):
    import dataclasses

    from distributed_cluster_gpus_tpu import sweep
    from distributed_cluster_gpus_tpu.evaluation import run_algo
    from distributed_cluster_gpus_tpu.sweep.compiler import cell_params
    from distributed_cluster_gpus_tpu.sweep.spec import (
        cell_fault_params, grid_base)

    out = str(tmp_path / "grid.json")
    col = str(tmp_path / "col")
    res = sweep.run_grid(grid, out, chunk_steps=chunk_steps,
                         columnar_dir=col, verbose=False)
    assert res["ran"] == len(grid_cells(grid))
    by_key = {cell_key(r): r for r in res["rows"]}

    # the columnar sibling carries the SAME values as the strict-JSON
    # artifact (both lower non-finite floats to null — a NaN p99 must
    # not survive in one artifact and not the other)
    with open(out) as f:
        json_rows = json.load(f)["rows"]
    assert ({json.dumps(r, sort_keys=True) for r in sweep.read_rows(col)}
            == {json.dumps(r, sort_keys=True) for r in json_rows})

    fleet, base = grid_base(grid)
    fp = cell_fault_params(grid, grid_cells(grid))
    for cell in grid_cells(grid):
        p = cell_params(base, cell, fp[cell])
        ref = run_algo(fleet, p, chunk_steps=chunk_steps).row()
        ref.update(cell.row_id())
        got = by_key[cell_key(ref)]
        assert (json.dumps(ref, sort_keys=True, default=float)
                == json.dumps(got, sort_keys=True, default=float)), \
            (cell.algo, cell.seed, cell.rate)

    # resume: a second run computes nothing
    res2 = sweep.run_grid(grid, out, chunk_steps=chunk_steps,
                          verbose=False)
    assert res2["ran"] == 0 and res2["skipped"] == len(grid_cells(grid))
    return res


def test_grid_bit_identical_duo(tmp_path):
    """Quick-tier golden: 2 algos x 2 chaos cells x 2 seeds on the duo
    fleet — every grid row must equal the serial run_algo row bit for
    bit (shared PRNG lowering + done-lane no-op stepping are load-
    bearing; any drift in either breaks this)."""
    grid = SweepGrid(axis="rates", rates=(0.0, 2.0),
                     algos=("default_policy", "eco_route"),
                     seeds=(123, 124), fleet="duo", duration=60.0)
    res = _assert_grid_matches_serial(grid, tmp_path)
    # rate 0 (empty FaultParams) and rate 2 (padded timelines) have
    # different state shapes: 2 shape-buckets per algo, 2 lanes each
    assert res["buckets"] == 4


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_grid_bit_identical_paper_fleet(tmp_path):
    """Slow-tier golden: the same anchor on the config-4 paper fleet
    (the shape chaos_sweep.py actually sweeps)."""
    grid = SweepGrid(axis="rates", rates=(0.0, 2.0),
                     algos=("default_policy", "joint_nf"),
                     seeds=(123,), duration=150.0)
    _assert_grid_matches_serial(grid, tmp_path)


# ---------------------------------------------------------------------------
# SIGKILL mid-grid -> per-bucket resume
# ---------------------------------------------------------------------------

def test_sigkill_mid_grid_resumes_missing_buckets(tmp_path):
    out = str(tmp_path / "sweep.json")
    cmd = [sys.executable, os.path.join(HERE, "scripts", "sweep_grid.py"),
           "--tiny", "--rates", "0", "--algos",
           "default_policy,eco_route", "--seeds", "123", "--duration",
           "60", "--chunk-steps", "256", "--json", out]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DCG_SWEEP_TEST_KILL_AFTER="1")
    p1 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        cwd=HERE, timeout=600)
    assert p1.returncode == -signal.SIGKILL, (p1.returncode, p1.stderr)
    partial = json.load(open(out))["rows"]
    assert len(partial) == 1  # exactly one flushed bucket survived

    env.pop("DCG_SWEEP_TEST_KILL_AFTER")
    p2 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        cwd=HERE, timeout=600)
    assert p2.returncode == 0, p2.stderr
    assert "(done)" in p2.stdout  # the banked bucket was skipped
    rows = json.load(open(out))["rows"]
    assert len(rows) == 2
    assert {r["algo"] for r in rows} == {"default_policy", "eco_route"}


# ---------------------------------------------------------------------------
# satellites: chaos_sweep argv note + row fields; ledger record kind
# ---------------------------------------------------------------------------

def test_chaos_sweep_argv_note_and_key_fields(tmp_path):
    out = str(tmp_path / "chaos.json")
    args = ["--tiny", "--rates", "0", "--algos", "default_policy",
            "--duration", "60", "--chunk-steps", "256", "--grid", "off",
            "--json", out]
    p = subprocess.run(
        [sys.executable, os.path.join(HERE, "scripts", "chaos_sweep.py")]
        + args, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, cwd=HERE, timeout=600)
    assert p.returncode == 0, p.stderr
    doc = json.load(open(out))
    # the note ends with the verbatim reproduce argv (satellite: the
    # interpolated fields alone cannot reconstruct the invocation)
    assert doc["note"].endswith(" ".join(args))
    assert "reproduce: python scripts/chaos_sweep.py" in doc["note"]
    (row,) = doc["rows"]
    # resume-key fields ride the row (satellite: seed/duration/mttr)
    assert row["seed"] == 123 and row["duration"] == 60.0
    assert row["mttr"] == 300.0


def test_ledger_ingests_sweep_grid_kind():
    from distributed_cluster_gpus_tpu.analysis import ledger

    doc = {"platform": "cpu", "sweep_grid_probe": {
        "fleet": "duo", "n_cells": 16, "n_buckets": 4,
        "grid_ev_s": 50000.0, "serial_ev_s": 20000.0,
        "grid_cells_s": 2.0, "serial_cells_s": 0.8,
        "speedup_cells": 2.5}}
    recs = ledger.records_from("bench_results/sweep_r16.json", doc)
    assert {r["kind"] for r in recs} == {"sweep_grid"}
    by_cfg = {r["config"]: r for r in recs}
    assert by_cfg["duo/16cells/grid"]["ev_s"] == 50000.0
    assert by_cfg["duo/16cells/grid"]["speedup"] == 2.5
    assert by_cfg["duo/16cells/serial"]["ev_s"] == 20000.0
    assert all(r["round"] == 16 for r in recs)
    # both arms survive the trend/gate plumbing
    assert len(ledger.series(recs)) == 2


def test_rate_fault_params_shared_budget():
    fp = spec.rate_fault_params([0.0, 0.5, 2.0], 600.0, 300.0)
    pos = [fp[r] for r in (0.5, 2.0)]
    assert len({p.max_outages_per_dc for p in pos}) == 1  # padded equal
    assert fp[0.0].outages == ()  # enabled-but-empty golden baseline
    assert np.all([p.enabled for p in pos])

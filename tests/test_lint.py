"""dcg-lint: every rule catches its fabricated violation, canonical
configs pass clean, and the baselines store round-trips byte-exactly.

Positive tests build MINIMAL violating programs (a scan-wrapped body,
mirroring the engine chunk shape) and assert the rule fires; negative
twins assert the clean/pinned variant passes.  The canonical-config
negative is the real gate: the shipped engine programs must lint clean
(allowlisted hits excepted — and every allowlist entry must carry a
written reason, enforced here too).
"""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from distributed_cluster_gpus_tpu.analysis import lint, rules, walker
from distributed_cluster_gpus_tpu.ops.physics import fmul_pinned


def make_ctx(body_fn, init_carry, *, name="fabricated", k=1,
             superstep_on=False, x64=False, baseline=None):
    """Wrap a carry->carry body in a length-8 scan (the engine chunk
    shape) and trace it into a LintContext."""
    def chunk(c):
        return jax.lax.scan(lambda c, _: (body_fn(c), None), c, None,
                            length=8)[0]

    jpr = jax.make_jaxpr(chunk)(init_carry)
    x64_jaxpr = None
    if x64:
        with jax.experimental.enable_x64():
            x64_jaxpr = jax.make_jaxpr(chunk)(init_carry).jaxpr
    scan_eqn = walker.main_scan_body(jpr, 8)
    return rules.LintContext(
        config=name, params=None, k=k, superstep_on=superstep_on,
        planner_on=True, forced_legacy=False, obs_on=False,
        jaxpr=jpr.jaxpr, scan_eqn=scan_eqn,
        body=scan_eqn.params["jaxpr"].jaxpr, scans=[scan_eqn],
        x64_jaxpr=x64_jaxpr, baseline=baseline,
        const_map=dict(zip(jpr.jaxpr.constvars, jpr.consts)))


def hits(ctx, rule_id):
    out, _ = rules.apply_rules(ctx, {rule_id})
    return [v for v in out if v.rule == rule_id]


# ---------------------------------------------------------------------------
# registry hygiene
# ---------------------------------------------------------------------------

def test_rule_registry_sane():
    assert len(rules.RULES) >= 9
    for rid, r in rules.RULES.items():
        assert rid == r.id
        assert r.severity in (rules.SEV_ERROR, rules.SEV_WARN)
        assert r.doc.strip(), f"{rid}: empty doc"
        assert rid == rid.lower() and " " not in rid, (
            f"{rid}: rule ids are kebab-case")


def test_allowlist_entries_carry_reasons():
    assert rules.ALLOWLIST, "the allowlist exists to document debt"
    for a in rules.ALLOWLIST:
        assert a.reason.strip(), f"{a.rule}/{a.match}: reason required"
        assert len(a.reason) > 40, (
            f"{a.rule}/{a.match}: a reason is prose, not a tag")
        assert a.rule in rules.RULES, f"{a.rule}: unknown rule id"


# ---------------------------------------------------------------------------
# positive / negative pairs, one per rule
# ---------------------------------------------------------------------------

def test_no_while_in_step_catches():
    def bad(c):
        return jax.lax.while_loop(lambda x: x < 10.0, lambda x: x + 1.0, c)

    assert hits(make_ctx(bad, jnp.float32(0)), "no-while-in-step")
    assert not hits(make_ctx(lambda c: c + 1.0, jnp.float32(0)),
                    "no-while-in-step")


def test_select_free_superstep_catches():
    def bad(c):
        return jax.lax.cond(c > 0, lambda x: x + 1.0, lambda x: x, c)

    ctx = make_ctx(bad, jnp.float32(0), k=4, superstep_on=True)
    assert hits(ctx, "select-free-superstep")
    # the same program at K=1 is legal (the event switch is a cond)
    assert not hits(make_ctx(bad, jnp.float32(0), k=1),
                    "select-free-superstep")


def test_host_callback_catches():
    def bad(c):
        jax.debug.print("c={c}", c=c)
        return c + 1.0

    assert hits(make_ctx(bad, jnp.float32(0)), "host-callback-in-graph")
    assert not hits(make_ctx(lambda c: c + 1.0, jnp.float32(0)),
                    "host-callback-in-graph")


def test_unfenced_float_product_catches():
    def bad(c):
        a, acc = c
        return (a, acc + a * 1.5)  # unpinned product -> accumulator

    def good(c):
        a, acc = c
        return (a, acc + fmul_pinned(a, 1.5))

    init = (jnp.float32(2.0), jnp.float32(0.0))
    assert hits(make_ctx(bad, init), "unfenced-float-product")
    assert not hits(make_ctx(good, init), "unfenced-float-product")


def test_duplicate_index_scatter_catches():
    def bad(c):
        idx = (c[:3] > 0).astype(jnp.int32)  # data-derived, can collide
        return c.at[idx].add(1.0, unique_indices=True)

    def good_no_claim(c):
        idx = (c[:3] > 0).astype(jnp.int32)
        return c.at[idx].add(1.0)  # well-defined duplicate semantics

    def good_iota(c):
        return c.at[jnp.arange(3)].add(1.0, unique_indices=True)

    init = jnp.zeros(4, jnp.float32)
    assert hits(make_ctx(bad, init), "duplicate-index-scatter-add")
    assert not hits(make_ctx(good_no_claim, init),
                    "duplicate-index-scatter-add")
    assert not hits(make_ctx(good_iota, init),
                    "duplicate-index-scatter-add")


def test_weak_type_promotion_catches():
    def bad(c):
        # weak Python-int chain: int64 under jax_enable_x64
        flags = jnp.where(c > 0, 1, jnp.where(c < -1.0, 2, 0))
        return c + flags.astype(jnp.float32)

    def good(c):
        flags = jnp.where(c > 0, jnp.int32(1),
                          jnp.where(c < -1.0, jnp.int32(2), jnp.int32(0)))
        return c + flags.astype(jnp.float32)

    init = jnp.float32(0)
    assert hits(make_ctx(bad, init, x64=True), "weak-type-promotion")
    assert not hits(make_ctx(good, init, x64=True), "weak-type-promotion")
    # an untraceable-under-x64 program is itself a finding
    ctx = make_ctx(good, init, x64=False)
    ctx.x64_error = "fabricated trace failure"
    assert hits(ctx, "weak-type-promotion")


def test_prng_key_reuse_catches():
    def bad(c):
        key, acc = c
        u = jax.random.uniform(key)          # consumes key
        z = jax.random.normal(key)           # ...and again: correlated
        return (key, acc + u + z)

    def good(c):
        key, acc = c
        key, k1, k2 = jax.random.split(key, 3)
        return (key, acc + jax.random.uniform(k1) + jax.random.normal(k2))

    def good_fold(c):
        key, acc = c
        u = jax.random.uniform(jax.random.fold_in(key, 0))
        z = jax.random.normal(jax.random.fold_in(key, 1))
        return (key, acc + u + z)

    init = (jax.random.key(0), jnp.float32(0))
    assert hits(make_ctx(bad, init), "prng-key-reuse")
    assert not hits(make_ctx(good, init), "prng-key-reuse")
    # distinct fold_in children off one parent are idiomatic, not reuse
    assert not hits(make_ctx(good_fold, init), "prng-key-reuse")


def test_f32_counter_overflow_catches():
    def bad(c):
        cnt, x = c
        return (cnt + 1.0, x)  # f32 carry += 1: stops at 2^24

    def good(c):
        cnt, x = c
        return (cnt + 1, x)    # int32 counter

    assert hits(make_ctx(bad, (jnp.float32(0), jnp.float32(0))),
                "f32-counter-overflow")
    assert not hits(make_ctx(good, (jnp.int32(0), jnp.float32(0))),
                    "f32-counter-overflow")


def test_eqn_ceiling_drift_catches():
    ctx = make_ctx(lambda c: (c + 1.0) * 2.0 - 3.0, jnp.float32(0),
                   baseline={"eqns": 1, "census": {"other": 1}})
    out = hits(ctx, "eqn-ceiling-drift")
    assert out and "grew" in out[0].message
    # no baseline entry at all -> actionable finding
    ctx2 = make_ctx(lambda c: c + 1.0, jnp.float32(0))
    out2 = hits(ctx2, "eqn-ceiling-drift")
    assert out2 and "--update-baselines" in out2[0].message
    # within ceiling -> clean
    n = walker.flat_count(ctx.body)
    ctx3 = make_ctx(lambda c: (c + 1.0) * 2.0 - 3.0, jnp.float32(0),
                    baseline={"eqns": n, "census": {}})
    assert not hits(ctx3, "eqn-ceiling-drift")


# ---------------------------------------------------------------------------
# the walker IS the one flattening rule
# ---------------------------------------------------------------------------

def test_walker_matches_historical_flatten():
    def legacy_flat(jaxpr):
        n = 0
        for q in jaxpr.eqns:
            n += 1
            for v in q.params.values():
                vs = v if isinstance(v, (list, tuple)) else [v]
                for x in vs:
                    if hasattr(x, "jaxpr"):
                        n += legacy_flat(x.jaxpr)
        return n

    def prog(c):
        def body(x):
            return jax.lax.cond(x[0] > 0, lambda y: y * 2.0,
                                lambda y: y + 1.0, x)

        c = jax.lax.scan(lambda a, _: (body(a), None), c, None,
                         length=4)[0]
        return jnp.sum(c ** 2)

    jpr = jax.make_jaxpr(prog)(jnp.ones(3, jnp.float32))
    assert walker.flat_count(jpr.jaxpr) == legacy_flat(jpr.jaxpr)
    census = walker.op_census(jpr.jaxpr)
    assert census["eqns"] == walker.flat_count(jpr.jaxpr)
    assert sum(v for k, v in census.items() if k != "eqns") \
        == census["eqns"], "census classes must partition the total"


# ---------------------------------------------------------------------------
# canonical configs lint clean (quick: two pillars; the full matrix is
# the slow-tier sweep + the lint_graph CLI / bench banking path)
# ---------------------------------------------------------------------------

def test_canonical_joint_nf_lints_clean(fleet):
    # one pillar config in the quick tier (K=4 exercises the superstep
    # rules + the x64 trace); the full 23-config matrix rides slow
    rep = lint.run_lint(fleet=fleet, config_names=["joint_nf/ring/K4"])
    assert rep["schema"] == "dcg.lint_report.v1"
    assert rep["checked"] == ["joint_nf/ring/K4"]
    assert rep["ok"], [v["message"] for v in rep["violations"]]
    # the allowlisted debt is visible, reasoned, and small
    for a in rep["allowlisted"]:
        assert a["reason"].strip()


def test_canonical_full_matrix_lints_clean(fleet):
    """Slow-tier acceptance gate: EVERY canonical config exits clean
    (ring+slab, K in {1,4,8}, planner/obs/signal/fault/chsac families)."""
    rep = lint.run_lint(fleet=fleet)
    assert len(rep["checked"]) == len(lint.canonical_configs())
    bad = [v for v in rep["violations"] if v["severity"] == "error"]
    assert not bad, [f"{v['config']}: [{v['rule']}] {v['message']}"
                     for v in bad]


# ---------------------------------------------------------------------------
# baselines: generated, and the update flow round-trips byte-identically
# ---------------------------------------------------------------------------

def test_baselines_in_tree_match_schema():
    b = lint.load_baselines()
    assert b["schema"] == lint.BASELINES_SCHEMA
    names = {c.name for c in lint.canonical_configs()}
    missing = names - set(b["configs"])
    assert not missing, (
        f"baselines missing {sorted(missing)} — run scripts/lint_graph.py "
        "--update-baselines")
    for name, e in b["configs"].items():
        assert e["eqns"] > 0
        if not e.get("derived"):
            assert sum(e["census"].values()) == e["eqns"], (
                f"{name}: census does not partition eqns")


def test_update_baselines_roundtrips_byte_identical(fleet, tmp_path):
    subset = [lint.config_by_name("joint_nf/ring/K1"),
              lint.config_by_name("joint_nf/slab/K1")]
    b1 = lint.generate_baselines(fleet, subset)
    p1, p2 = str(tmp_path / "b1.json"), str(tmp_path / "b2.json")
    lint.dump_baselines(b1, p1)
    # regenerate from scratch: same code, same bytes
    b2 = lint.generate_baselines(fleet, subset)
    lint.dump_baselines(b2, p2)
    with open(p1, "rb") as f1, open(p2, "rb") as f2:
        assert f1.read() == f2.read(), (
            "--update-baselines must round-trip byte-identically")
    # and the loader accepts its own output
    loaded = lint.load_baselines(p1)
    assert loaded["configs"]["joint_nf/ring/K1"]["eqns"] \
        == b1["configs"]["joint_nf/ring/K1"]["eqns"]
    # the round-trip diff is empty; a fabricated drift is reported
    assert not lint.diff_baselines(b1, b2)
    b3 = json.loads(json.dumps(b2))
    b3["configs"]["joint_nf/ring/K1"]["eqns"] += 7
    assert any("joint_nf/ring/K1" in line
               for line in lint.diff_baselines(b1, b3))


def test_in_tree_baseline_matches_live_trace(fleet):
    """The committed baseline for the pillar config equals a live trace —
    the tree and the banked ceilings cannot drift apart silently."""
    ctx = lint.trace_config(fleet, lint.config_by_name("joint_nf/ring/K1"),
                            x64=False)
    assert walker.flat_count(ctx.body) \
        == lint.measured_for("joint_nf/ring/K1")


# ---------------------------------------------------------------------------
# the shared report schema
# ---------------------------------------------------------------------------

def test_report_schema_shape():
    from distributed_cluster_gpus_tpu.analysis import report

    rep = report.make_report(
        "validate_workload", ["spec.json"],
        [report.violation("bad rate", rule="validate_workload",
                          where="spec.json")])
    assert rep["schema"] == "dcg.lint_report.v1"
    assert not rep["ok"]
    v = rep["violations"][0]
    assert set(v) == {"rule", "severity", "config", "where", "message"}
    clean = report.make_report("validate_workload", ["spec.json"], [])
    assert clean["ok"] and "OK" in clean["summary"]

"""Superstep event coalescing: K > 1 must be invisible in the results.

The engine's superstep mode (SimParams.superstep_k) applies up to K
causally-commuting events per scan iteration through a fused branchless
handler; every window that fails the commutation predicate degenerates to
the exact singleton body.  The contract tested here is the strongest one
possible: K in {2, 4, 8} runs are BIT-IDENTICAL to K=1 — same final
SimState down to the PRNG key, byte-identical CSV logs — across both
queue layouts and several algorithm families, plus a faults-on config
that is statically forced to singleton.

Since round 10 (workload compiler) the arrival pregen is chunk-invariant
— left-fold carries + epoch-anchored inversion — so bit-identity across
K holds across ANY chunking too; the historical "chunk-boundary pregen
re-anchoring" caveat is retired and
`test_chunk_boundary_continuity_exact` pins the stronger contract.
"""

import dataclasses
import filecmp

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_cluster_gpus_tpu.models import JobStatus, SimParams
from distributed_cluster_gpus_tpu.sim.engine import Engine, init_state
from distributed_cluster_gpus_tpu.sim.io import drain_emissions, run_simulation


from conftest import tree_mismatches as _tree_mismatches


def _golden_pair(fleet, tmp_path, k, chunk_steps=8192, **kw):
    """Run K=1 and K=k from the same seed; assert states and CSVs match.

    Every leaf must match EXCEPT ``.key``: the main PRNG chain advances
    one split per scan ITERATION even on post-``done`` no-op steps
    (singleton semantics), and K changes how many trailing no-op
    iterations a fixed-size chunk has.  Every EVENT consumes the same
    chain key either way (pre-done, iteration i fires exactly the events
    the chain position covers), so all results — and therefore all other
    leaves — are bit-identical; the residual key position is not a
    result."""
    outs, states = {}, {}
    for kk in (1, k):
        params = SimParams(superstep_k=kk, **kw)
        out = str(tmp_path / f"k{kk}")
        states[kk] = run_simulation(fleet, params, out_dir=out,
                                    chunk_steps=chunk_steps)
        outs[kk] = out
    bad = [p for p in _tree_mismatches(states[1], states[k])
           if p != ".key"]
    assert not bad, f"K={k} diverged from K=1 in: {bad}"
    for name in ("cluster_log.csv", "job_log.csv"):
        assert filecmp.cmp(f"{outs[1]}/{name}", f"{outs[k]}/{name}",
                           shallow=False), f"{name} differs at K={k}"
    assert int(states[k].n_events) > 0
    return states[k]


GOLDEN_KW = dict(duration=60.0, log_interval=5.0, inf_mode="sinusoid",
                 inf_rate=2.0, trn_mode="poisson", trn_rate=0.1,
                 job_cap=128, lat_window=256, seed=3, queue_cap=256)


@pytest.mark.parametrize("algo,queue_mode,k", [
    ("default_policy", "ring", 4),
    ("default_policy", "slab", 4),
    ("joint_nf", "ring", 8),
    ("carbon_cost", "slab", 2),
    ("eco_route", "ring", 4),  # single-DC routing: near-total degeneration
])
def test_golden_bit_identical_across_k(fleet, tmp_path, algo, queue_mode, k):
    st = _golden_pair(fleet, tmp_path, k, algo=algo, queue_mode=queue_mode,
                      **GOLDEN_KW)
    assert int(st.n_finished.sum()) > 20  # the golden actually did work


def test_golden_power_cap_controller(fleet, tmp_path):
    """Log-tick cap controllers truncate every window (logs never fuse) —
    the golden must still hold with the controller active."""
    _golden_pair(fleet, tmp_path, 4, algo="cap_greedy", power_cap=20000.0,
                 **GOLDEN_KW)


def test_golden_faults_superstep(fleet, tmp_path):
    """Round 12: fault runs are superstep-ELIGIBLE — EV_FAULT windows
    degenerate to L=1 through the masked slot-0 `_handle_fault`, fused
    windows require an empty PREEMPTED backlog (so the migration sweep
    stays per-event), and every start clamps to the straggler derate.
    The K=8 program is now the REAL fused program, and the golden pins
    it bit-identical to the singleton across outage, derate, and WAN
    windows (fault_log.csv included via the state compare + CSVs)."""
    from distributed_cluster_gpus_tpu.models import FaultParams

    faults = FaultParams(
        outages=tuple((d, 4.0 + 2.0 * d, 14.0 + 2.0 * d) for d in range(6)),
        derates=((1, 3.0, 20.0, 0.6), (3, 6.0, 25.0, 0.6)),
        wan=((0, 2, 2.0, 25.0, 3.0, 0.1),))
    kw = dict(GOLDEN_KW, algo="default_policy", trn_rate=1.0, faults=faults)
    assert Engine(fleet, SimParams(superstep_k=8, **kw)).superstep_on
    st = _golden_pair(fleet, tmp_path, 8, **kw)
    assert int(st.fault.n_preempted) > 0  # the chaos was real
    assert int(st.fault.n_migrated) > 0
    assert filecmp.cmp(str(tmp_path / "k1" / "fault_log.csv"),
                       str(tmp_path / "k8" / "fault_log.csv"),
                       shallow=False), "fault_log.csv differs at K=8"


def test_golden_signals_superstep(fleet, tmp_path):
    """Round 12: signal-timeline runs are superstep-ELIGIBLE — the fused
    body accrues the price/carbon cost integral per sub-step and the
    eco admission/routing samples the timelines at each slot's own
    event time.  K=4 must reproduce the K=1 run bit-for-bit, cost and
    carbon accumulators and the SIGNAL_CLUSTER_COLS columns included."""
    import numpy as np

    from distributed_cluster_gpus_tpu.workload import make_preset

    wl = make_preset("legacy_signals", fleet)
    kw = dict(GOLDEN_KW, algo="carbon_cost", workload=wl,
              inf_mode="sinusoid", trn_mode="poisson")
    assert Engine(fleet, SimParams(superstep_k=4, **kw)).superstep_on
    st = _golden_pair(fleet, tmp_path, 4, **kw)
    assert float(np.sum(np.asarray(st.signals.cost_usd))) > 0.0
    assert float(np.sum(np.asarray(st.signals.carbon_g))) > 0.0


def test_golden_multichunk_pregen_off(fleet, tmp_path, monkeypatch):
    """Across chunk boundaries the in-step arrival draws are the chunk-
    stable path; K changes the events-per-chunk coverage, and results
    must STILL be bit-identical."""
    monkeypatch.setenv("DCG_ARRIVAL_PREGEN", "0")
    _golden_pair(fleet, tmp_path, 4, chunk_steps=512,
                 algo="default_policy", **GOLDEN_KW)


def test_chunk_boundary_continuity_exact(fleet, tmp_path, monkeypatch):
    """Round-10 tentpole pin: the workload compiler's pregen is
    CHUNK-INVARIANT (left-fold carries in `SimState.next_arrival` /
    ``arr_cum``, epoch-anchored inversion), so the historical
    "re-anchoring ulp caveat" of rounds 6-9 is retired — and this test
    replaces its macro-tolerance clause with exact bit-identity:

    (a) a SINGLE-chunk pregen-on run is bit-identical across K (the
        whole run completes inside chunk 0);
    (b) a multi-chunk run with ``DCG_ARRIVAL_PREGEN=0`` (the thinning
        replay backend — the legacy draw realization) is bit-identical
        across K;
    (c) a MULTI-chunk pregen-on run is bit-identical across K — and to
        the single-chunk run of (a), CSV bytes included.  If this ever
        needs a tolerance again, a generator stopped being a pure
        function of (seed, draw index) + composable carries.
    """
    kw = dict(GOLDEN_KW, algo="default_policy", queue_mode="ring")

    # (a) single-chunk, pregen on: exact — and actually single-chunk
    params1 = SimParams(superstep_k=1, **kw)
    st_one = run_simulation(fleet, params1, out_dir=str(tmp_path / "one"),
                            chunk_steps=16384, max_chunks=1)
    assert bool(st_one.done), (
        "pin (a) is vacuous: the run no longer fits one chunk — raise "
        "chunk_steps")
    _golden_pair(fleet, tmp_path / "one_chunk", 4, chunk_steps=16384, **kw)

    # (b) multi-chunk, thinning backend (the legacy draw realization)
    with monkeypatch.context() as mp:
        mp.setenv("DCG_ARRIVAL_PREGEN", "0")
        st_mc = _golden_pair(fleet, tmp_path / "mc_off", 4,
                             chunk_steps=512, **kw)
        # multi-chunk for real, or (b) collapses into (a)
        assert int(st_mc.n_events) > 0 and not bool(
            run_simulation(fleet, params1, out_dir=None, chunk_steps=512,
                           max_chunks=1).done)

    # (c) multi-chunk, pregen ON: exact across K and vs single-chunk
    st_mc_on = _golden_pair(fleet, tmp_path / "mc_on", 4,
                            chunk_steps=512, **kw)
    bad = [p for p in _tree_mismatches(st_one, st_mc_on) if p != ".key"]
    assert not bad, (
        f"multi-chunk pregen-on diverged from single-chunk in: {bad} — "
        "the chunk-invariance contract broke")
    for name in ("cluster_log.csv", "job_log.csv"):
        assert filecmp.cmp(str(tmp_path / "one" / name),
                           str(tmp_path / "mc_on" / "k4" / name),
                           shallow=False), (
            f"{name}: chunked K=4 bytes differ from the single-chunk run")


def test_superstep_actually_amortizes(fleet):
    """Anti-vacuity: at the bench shape the fused path must FIRE — the
    K=4 engine advances well over one event per scan iteration."""
    kw = dict(algo="default_policy", duration=1e9, log_interval=20.0,
              inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
              trn_rate=0.1, job_cap=128, lat_window=512, seed=0,
              queue_cap=256)
    e4 = Engine(fleet, SimParams(superstep_k=4, **kw))
    s4 = init_state(jax.random.key(0), fleet, SimParams(superstep_k=4, **kw))
    s4, em = e4.run_chunk(s4, None, n_steps=512)
    assert int(s4.n_events) > 512 * 1.5, (
        f"only {int(s4.n_events)} events in 512 iterations — the "
        "commutation predicate has (re)grown too conservative")
    # K-wide emission shapes
    assert em["job_valid"].shape == (512, 4)
    assert em["job"].shape[:2] == (512, 4)


# ---------------------------------------------------------------------------
# commutation predicate unit tests (crafted windows)
# ---------------------------------------------------------------------------

PRED_KW = dict(algo="default_policy", duration=1e9, log_interval=1e6,
               inf_mode="off", trn_mode="off", job_cap=32, lat_window=64,
               seed=0, queue_cap=64, superstep_k=4)


def _crafted(fleet, dcs, sizes):
    """A state whose only pending events are RUNNING-job finishes."""
    params = SimParams(**PRED_KW)
    eng = Engine(fleet, params)
    st = init_state(jax.random.key(0), fleet, params)
    J = params.job_cap
    status = np.zeros(J, np.int32)
    dc = np.zeros(J, np.int32)
    n = np.zeros(J, np.int32)
    f_idx = np.zeros(J, np.int32)
    seq = np.zeros(J, np.int32)
    size = np.zeros(J, np.float32)
    spu = np.zeros(J, np.float32)
    watts = np.zeros(J, np.float32)
    busy = np.zeros(fleet.n_dc, np.int32)
    for i, (d, sz) in enumerate(zip(dcs, sizes)):
        status[i], dc[i], n[i], f_idx[i], seq[i] = (
            JobStatus.RUNNING, d, 1, fleet.n_f - 1, i + 1)
        size[i] = sz
        T, P = eng._row_TP(jnp.int32(d), jnp.int32(0), jnp.int32(1),
                           jnp.int32(fleet.n_f - 1))
        spu[i], watts[i] = float(T), float(P)
        busy[d] += 1
    st = st.replace(
        jobs=st.jobs.replace(
            status=jnp.asarray(status), dc=jnp.asarray(dc),
            n=jnp.asarray(n), f_idx=jnp.asarray(f_idx),
            seq=jnp.asarray(seq), size=jnp.asarray(size),
            spu=jnp.asarray(spu), watts=jnp.asarray(watts)),
        dc=st.dc.replace(busy=jnp.asarray(busy)),
        started_accrual=jnp.bool_(True),
    )
    return eng, st


def test_predicate_fuses_distinct_dcs(fleet):
    eng, st = _crafted(fleet, dcs=[0, 1, 2], sizes=[1.0, 2.0, 3.0])
    assert eng.superstep_on
    sel = eng._superstep_select(st)
    assert bool(sel["fused_ok"])
    assert int(sel["m"]) == 3
    assert [bool(v) for v in np.asarray(sel["slots"]["valid"])] == [
        True, True, True, False]


def test_predicate_rejects_same_dc(fleet):
    """Two finishes at ONE DC do not commute through the fused handler
    (shared busy/ladder/drain state) — the window truncates before the
    second and a 1-event window falls back to the singleton body."""
    eng, st = _crafted(fleet, dcs=[0, 0], sizes=[1.0, 2.0])
    sel = eng._superstep_select(st)
    assert not bool(sel["fused_ok"])
    assert int(sel["m"]) == 1


def test_predicate_rejects_same_dc_tie(fleet):
    """Crafted same-DC TIE: equal finish times at one DC — the singleton
    path resolves these on consecutive zero-dt steps, and the superstep
    must leave that order exactly alone."""
    eng, st = _crafted(fleet, dcs=[3, 3], sizes=[2.0, 2.0])
    sel = eng._superstep_select(st)
    assert not bool(sel["fused_ok"])


def test_predicate_rejects_cross_dc_tied_finishes(fleet):
    """Even at distinct DCs, bit-equal finish times fail the separation
    check: a position->=1 finish is re-derived from accumulated progress
    at apply time, and only a >margin gap guarantees the re-derivation
    cannot reorder the window."""
    eng, st = _crafted(fleet, dcs=[0, 1], sizes=[1.0, 1.0])
    # per-DC physics differ, so force bit-equal finish times by cloning
    # the cached seconds-per-unit across the two rows
    st = st.replace(jobs=st.jobs.replace(
        spu=st.jobs.spu.at[1].set(st.jobs.spu[0])))
    sel = eng._superstep_select(st)
    # times now bit-equal -> the position-1 finish lacks separation
    assert not bool(sel["fused_ok"])


def test_static_ineligibility():
    """Round-12 residue: only chsac_af / bandit / weighted routing still
    compile the singleton program no matter what superstep_k says —
    fault and signal-timeline runs are eligible now, and the reasons
    ride `Engine.ineligibility` (see also the census regression pin in
    test_perf_structure::test_eligibility_residue_pinned)."""
    from distributed_cluster_gpus_tpu.configs import build_fleet
    from distributed_cluster_gpus_tpu.configs.paper import build_incident_faults
    from distributed_cluster_gpus_tpu.workload import make_preset

    fleet = build_fleet()
    base = dict(duration=60.0, log_interval=5.0, inf_mode="poisson",
                inf_rate=2.0, trn_mode="off", job_cap=64, lat_window=64,
                seed=0, superstep_k=4)
    assert Engine(fleet, SimParams(algo="default_policy", **base)).superstep_on
    assert not Engine(fleet, SimParams(algo="bandit", **base)).superstep_on
    assert not Engine(
        fleet, SimParams(algo="default_policy",
                         router_weights=(1.0, 0.0, 0.0, 0.0, 0.0),
                         **base)).superstep_on
    # round 12: the two big production families joined the fast path
    assert Engine(
        fleet, SimParams(algo="default_policy",
                         faults=build_incident_faults(10.0, 20.0),
                         **base)).superstep_on
    assert Engine(
        fleet, SimParams(algo="carbon_cost",
                         workload=make_preset("legacy_signals", fleet),
                         **base)).superstep_on
    with pytest.raises(ValueError, match="superstep_k"):
        SimParams(algo="default_policy",
                  **{**base, "superstep_k": 99})


def test_superstep_shard_parity(fleet):
    """Round 7: the unified select-free K>1 body must stay bit-parity
    safe under shard_map — round 6's K>1 program was mostly the
    already-parity-tested singleton `_step` riding a cond; now the whole
    body is the fused/masked path, so it needs its own mesh coverage."""
    from distributed_cluster_gpus_tpu.parallel.mesh import make_mesh
    from distributed_cluster_gpus_tpu.parallel.rollout import (
        engine_shard_parity)

    params = SimParams(algo="joint_nf", duration=1e9, log_interval=20.0,
                       inf_mode="sinusoid", inf_rate=6.0, trn_mode="poisson",
                       trn_rate=0.1, job_cap=64, lat_window=128, seed=0,
                       queue_mode="ring", queue_cap=128, superstep_k=4)
    assert Engine(fleet, params).superstep_on
    engine_shard_parity(fleet, params, make_mesh(4), n_rollouts=8,
                        chunk_steps=16)


def test_unified_body_handles_log_and_drain_degeneration(fleet):
    """Slot-0 singleton semantics inside the unified body: a config with
    constant queue pressure (tiny job_cap spills work into the rings) and
    frequent log ticks exercises the masked log handler and the masked
    post-finish drain on nearly every window — and must still match K=1
    bit-for-bit.  (The wide goldens cover the healthy regime; this pins
    the degenerate one.)"""
    import dataclasses

    kw = dict(GOLDEN_KW, job_cap=8, queue_cap=512, log_interval=2.0,
              inf_rate=4.0, algo="default_policy")
    states = {}
    for kk in (1, 4):
        params = SimParams(superstep_k=kk, **kw)
        eng = Engine(fleet, params)
        st = init_state(jax.random.key(1), fleet, params)
        st, _ = eng.run_chunk(st, None, n_steps=4096)
        states[kk] = st
    bad = [p for p in _tree_mismatches(states[1], states[4]) if p != ".key"]
    assert not bad, f"degenerate-regime K=4 diverged: {bad}"
    # the tiny slab must actually have queued work (drains were real)
    q = states[1].queues
    assert int(jnp.sum(q.tail)) > 0


def test_drain_emissions_handles_k_wide_job_slabs():
    """io: [n_steps, K] job emissions flatten chronologically."""
    em = {
        "cluster_valid": np.zeros(3, bool),
        "cluster": np.zeros((3, 8, 14), np.float32),
        "job_valid": np.array([[False, True], [True, True], [False, False]]),
        "job": np.arange(3 * 2 * 15, dtype=np.float32).reshape(3, 2, 15),
    }
    stats = drain_emissions(em, writers=None)
    assert stats["job_rows"] == 3
    assert stats["cluster_rows"] == 0

"""CLI for the algorithm-comparison harness over the five BASELINE configs.

    python eval.py --config 4 --duration 600          # one config
    python eval.py --all --duration 300 --json out.json

Writes a markdown table to stdout and (optionally) a JSON file the judge /
CI can diff across rounds.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, choices=[1, 2, 3, 4, 5], default=None)
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--duration", type=float, default=600.0)
    ap.add_argument("--chunk-steps", type=int, default=4096)
    ap.add_argument("--json", default=None)
    ap.add_argument("--warmstart", action="store_true",
                    help="offline-pretrained vs cold CHSAC-AF on config 4")
    ap.add_argument("--pretrain-steps", type=int, default=2000)
    a = ap.parse_args(argv)

    from distributed_cluster_gpus_tpu.evaluation import (
        baseline_config, compare, eval_config5, eval_warmstart,
    )

    if a.warmstart:
        print("=== offline warm-start vs cold (config-4 workload)")
        rows = eval_warmstart(duration=a.duration,
                              pretrain_steps=a.pretrain_steps,
                              chunk_steps=a.chunk_steps)
        if a.json:
            with open(a.json, "w") as f:
                json.dump({"warmstart": [s.row() for s in rows]}, f,
                          indent=2, default=float)
            print(f"wrote {a.json}")
        return

    configs = list(range(1, 6)) if a.all else [a.config or 4]
    results = {}
    for n in configs:
        print(f"=== BASELINE config {n}")
        if n == 5:
            results["config5_ppo"] = eval_config5()
            continue
        spec = baseline_config(n, a.duration)
        import dataclasses

        summaries = compare(spec["fleet"], spec["base"], spec["algos"],
                            chunk_steps=a.chunk_steps)
        results[f"config{n}"] = [s.row() for s in summaries]

    if a.json:
        with open(a.json, "w") as f:
            json.dump(results, f, indent=2, default=float)
        print(f"wrote {a.json}")


if __name__ == "__main__":
    main()

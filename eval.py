"""CLI for the algorithm-comparison harness over the five BASELINE configs.

    python eval.py --config 4 --duration 600            # one config
    python eval.py --all --duration 300 --json out.json
    python eval.py --config 3 --seeds 3                 # mean±sd over seeds
    python eval.py --config 3c                          # diagnostic variants

Writes a markdown table to stdout and (optionally) a JSON file the judge /
CI can diff across rounds.  With ``--seeds N`` every algorithm runs on N
workload realizations and the JSON carries per-seed rows plus mean±sd
aggregates.  chsac_af on config 4 runs through the distributed trainer
(``--rollouts``, default 8) — the same configuration the benchmark
measures; rollout 0's workload matches the heuristics' single world.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    import jax

    jax.config.update("jax_platforms", "cpu")

CONFIG_CHOICES = ["1", "2", "3", "4", "5", "3c", "3s", "4s"]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", choices=CONFIG_CHOICES, default=None)
    ap.add_argument("--all", action="store_true",
                    help="configs 1-5 (not the diagnostic variants)")
    ap.add_argument("--duration", type=float, default=600.0)
    ap.add_argument("--chunk-steps", type=int, default=4096)
    ap.add_argument("--seeds", type=int, default=1,
                    help="workload realizations per algorithm (>=3 for "
                         "mean±sd aggregates)")
    ap.add_argument("--seed0", type=int, default=123,
                    help="first seed; runs use seed0..seed0+seeds-1")
    ap.add_argument("--rollouts", type=int, default=8,
                    help="distributed-trainer rollouts for chsac_af on "
                         "config 4/4s (1 = single-world train_chsac)")
    ap.add_argument("--algos", default=None,
                    help="comma list restricting a config's algorithm set "
                         "(e.g. --config 5 --algos ppo to run only the PPO "
                         "rows and merge with banked config-4 rows)")
    ap.add_argument("--ppo-scale", type=int, default=None, metavar="R",
                    help="run the config-5 PPO throughput point at R "
                         "rollouts (events/s + platform) instead of a "
                         "policy-quality comparison")
    ap.add_argument("--json", default=None)
    ap.add_argument("--warmstart", action="store_true",
                    help="offline-pretrained vs cold CHSAC-AF on config 4")
    ap.add_argument("--pretrain-steps", type=int, default=2000)
    ap.add_argument("--critic-arch", choices=["onehot", "heads"],
                    default=None,
                    help="override the config-4 critic for --warmstart "
                         "(both arms; 'heads' is ~30x cheaper per update "
                         "on CPU)")
    a = ap.parse_args(argv)

    from distributed_cluster_gpus_tpu.evaluation import (
        baseline_config, compare_seeds, eval_config5, eval_warmstart,
        variant_config,
    )

    if a.warmstart:
        print("=== offline warm-start vs cold (config-4 workload)")
        rows = eval_warmstart(duration=a.duration,
                              pretrain_steps=a.pretrain_steps,
                              chunk_steps=a.chunk_steps,
                              critic_arch=a.critic_arch)
        if a.json:
            # strict-JSON portability: bare NaN tokens break jq/JS
            from distributed_cluster_gpus_tpu.utils.jsonio import \
                dump_json_atomic

            dump_json_atomic(a.json, {"warmstart": [s.row() for s in rows]})
            print(f"wrote {a.json}")
        return

    if a.ppo_scale:
        print(f"=== config-5 PPO throughput point, R={a.ppo_scale}")
        out = eval_config5(n_rollouts=a.ppo_scale)
        print(f"  {out['events_per_sec']:.0f} events/s on {out['platform']}")
        if a.json:
            from distributed_cluster_gpus_tpu.utils.jsonio import \
                dump_json_atomic

            dump_json_atomic(a.json, {"config5_ppo_scale": out})
            print(f"wrote {a.json}")
        return

    configs = [str(c) for c in range(1, 6)] if a.all else [a.config or "4"]
    seeds = list(range(a.seed0, a.seed0 + a.seeds))
    results = {}
    for n in configs:
        print(f"=== BASELINE config {n}")
        spec = (variant_config(n, a.duration) if n in ("3c", "3s", "4s")
                else baseline_config(int(n), a.duration))
        if a.algos:
            keep = [s.strip() for s in a.algos.split(",") if s.strip()]
            unknown = set(keep) - set(spec["algos"])
            if unknown:
                ap.error(f"--algos {sorted(unknown)} not in config {n}'s "
                         f"set {spec['algos']}")
            spec["algos"] = keep
        rollouts = a.rollouts if n in ("4", "4s", "5") else 1
        # always the seeded structure (per_seed + run_shape), even for one
        # seed: artifacts stay mergeable/assemblable and stamped with the
        # engine run-shape regardless of campaign sharding
        out = compare_seeds(
            spec["fleet"], spec["base"], spec["algos"], seeds,
            chunk_steps=a.chunk_steps, rollouts=rollouts)
        results[f"config{n}"] = out
        if a.seeds > 1:
            print(f"  -- aggregate over {a.seeds} seeds (mean±sd)")
            for agg in out["aggregate"]:
                print(f"  {agg['algo']:>15s}: "
                      f"{agg['energy_kwh_mean']:9.2f}±{agg['energy_kwh_sd']:.2f} kWh, "
                      f"p99_inf {agg['p99_lat_inf_s_mean']:.4f}"
                      f"±{agg['p99_lat_inf_s_sd']:.4f}s, "
                      f"done {agg['completed_inf_mean']:.0f}"
                      f"+{agg['completed_trn_mean']:.0f}, "
                      f"Wh/unit {agg['energy_per_unit_wh_mean']:.4f}"
                      f"±{agg['energy_per_unit_wh_sd']:.4f}")

    if a.json:
        from distributed_cluster_gpus_tpu.utils.jsonio import dump_json_atomic

        dump_json_atomic(a.json, results)
        print(f"wrote {a.json}")


if __name__ == "__main__":
    main()
